#!/usr/bin/env python3
"""Distributed job launcher (reference ``tools/launch.py`` +
``dmlc_tracker``; SURVEY.md §4.4, L10).

Reference protocol: start a scheduler, then ssh/local-exec N workers and S
servers with ``DMLC_*`` env vars pointing at it.

TPU-native protocol: there are no server/scheduler roles — one process per
host joins a ``jax.distributed`` group via a coordinator address.  This
launcher keeps the reference CLI shape::

    python tools/launch.py -n 4 --launcher local  python train.py ...
    python tools/launch.py -n 4 --launcher ssh -H hosts  python train.py ...

and sets, for each rank:

    MXNET_COORDINATOR       host:port of rank 0 (feeds
                            jax.distributed.initialize; read by
                            mxnet_tpu.parallel.init_distributed)
    MXNET_NUM_WORKERS       total ranks
    MXNET_WORKER_ID         this rank
    MXNET_HEARTBEAT_FILE    per-rank beat file (local mode; written by
                            mxnet_tpu.parallel.heartbeat)
    DMLC_ROLE=worker        reference compat (server/scheduler ranks can be
                            requested with -s but are deprecated no-ops)

Supervision (ISSUE 13, the reference tracker's dead-worker detection):
in local mode the launcher is a real supervisor, not a wait() loop.  It
collects every rank's heartbeat file and last log lines, and on a
failed rank — nonzero/signal exit, or a heartbeat silent past
``--heartbeat-timeout`` once the rank has started beating — it prints
a diagnostic naming the rank and its last output, kills the remaining
ranks (SIGTERM, then SIGKILL after ``--kill-grace``), reaps them, and
exits with the FIRST failing rank's code (``128+signal`` for signal
deaths) instead of hanging in a half-dead rendezvous.  Ranks that
never beat (commands that don't import mxnet_tpu) are supervised on
process exit alone, so plain commands behave exactly as before.

Supervised restart (ISSUE 15, the recovery half): with ``--restarts N``
a dead/wedged rank no longer ends the job — the supervisor tears down
ALL ranks (the same hardened ``_kill_all``), waits a doubling backoff
(``--restart-backoff``), and re-spawns the whole pod on a fresh
coordinator port.  Ranks auto-resume from the newest COMPLETE
checkpoint: ``--checkpoint-dir D`` exports ``MXNET_CHECKPOINT_DIR=D``
so ``mx.checkpoint.restore(step=None)`` / the Estimator's
``AtomicCheckpointHandler`` find it, and every spawn exports
``MXNET_RESTART_COUNT`` (0 on the first launch) so rank code can
branch per attempt (chaos scripts re-arm ``MXNET_FAULT_INJECT`` — or
don't — based on it; the supervisor itself never rewrites the spec).
The budget is counted PER DISTINCT FAILURE ``(rank, why)``: a rank
flapping the same way N times exhausts its budget and the job fails
with that rank's code, while a brand-new failure gets its own N —
restart storms stay bounded without a single global counter starving
unrelated recoveries.  Each restart emits a ``pod_restart`` event +
``launch_pod_restarts_total`` counter; ``tools/telemetry_report.py``
renders them in its restarts section.  An operator signal
(SIGINT/SIGTERM) is never restarted.  Local mode only.
"""
from __future__ import annotations

import argparse
import os
import shlex
import shutil
import signal
import socket
import subprocess
import sys
import tempfile
import threading
import time
from collections import deque


def _free_port():
    s = socket.socket()
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _rank_env(args, coordinator, rank):
    env = dict(os.environ)
    env.update({
        "MXNET_COORDINATOR": coordinator,
        "MXNET_NUM_WORKERS": str(args.num_workers),
        "MXNET_WORKER_ID": str(rank),
        # reference-compatible names (SURVEY.md §4.4 env protocol)
        "DMLC_ROLE": "worker",
        "DMLC_NUM_WORKER": str(args.num_workers),
        "DMLC_NUM_SERVER": str(args.num_servers),
        "DMLC_PS_ROOT_URI": coordinator.split(":")[0],
        "DMLC_PS_ROOT_PORT": coordinator.split(":")[1],
    })
    return env


def _emit(kind, **fields):
    """Best-effort telemetry from the supervisor process (lands in the
    ring / an attached ``MXNET_TELEMETRY_JSONL`` sink).  The supervisor
    must stay usable without the library importable, so a failed import
    is silence, not a crash."""
    try:
        from mxnet_tpu import telemetry
    except Exception:
        return
    telemetry.emit(kind, **fields)
    if kind == "worker_dead":
        telemetry.counter("launch_worker_dead_total").inc()
    elif kind == "pod_restart":
        telemetry.counter("launch_pod_restarts_total").inc()


class _Rank:
    """One supervised local rank: process + heartbeat file + a tail of
    its interleaved stdout/stderr for the failure diagnostic."""

    def __init__(self, rank, proc, hb_path):
        self.rank = rank
        self.proc = proc
        self.hb_path = hb_path
        self.last_mtime = None       # wall-clock mtime last observed
        self.last_beat_mono = None   # monotonic instant it changed
        self.tail = deque(maxlen=40)
        self.reader = threading.Thread(
            target=self._read, name=f"launch-rank{rank}-log",
            daemon=True)
        self.reader.start()

    def _read(self):
        # line-for-line passthrough (tests and operators read the
        # ranks' prints from the launcher's stdout, as before) + a
        # bounded tail kept for the post-mortem
        for line in self.proc.stdout:
            self.tail.append(line)
            sys.stdout.write(line)
            sys.stdout.flush()

    def heartbeat_age(self):
        """Monotonic seconds since this rank's beat file last CHANGED
        (None until the first beat is seen).  mtime values are only
        compared for equality against each other, never against a
        clock — the age itself comes from ``time.monotonic()``, so an
        NTP step cannot fake a stale (or fresh) heartbeat."""
        try:
            mt = os.path.getmtime(self.hb_path)
        except OSError:
            return None   # not beating (or beat dir already gone)
        if mt != self.last_mtime:
            self.last_mtime = mt
            self.last_beat_mono = time.monotonic()
        return time.monotonic() - self.last_beat_mono


def _kill_all(ranks, grace=5.0):
    """SIGTERM every live rank, escalate to SIGKILL after ``grace``
    seconds, and reap everything — no zombies, no survivors holding
    the coordinator port.  Accepts ``_Rank`` objects or bare Popens
    (the ssh branch)."""
    procs = [getattr(r, "proc", r) for r in ranks]
    for p in procs:
        if p.poll() is None:
            try:
                p.terminate()
            except OSError:
                pass
    deadline = time.monotonic() + max(grace, 0.0)
    while time.monotonic() < deadline and \
            any(p.poll() is None for p in procs):
        time.sleep(0.05)
    for p in procs:
        if p.poll() is None:
            try:
                p.kill()
            except OSError:
                pass
    for p in procs:
        try:
            p.wait(timeout=10)
        except (subprocess.TimeoutExpired, OSError):
            pass


def _exit_code(returncode):
    """Shell convention: a signal death (negative Popen returncode)
    forwards as 128+signal; anything else forwards as-is."""
    if returncode is None:
        return 1
    return 128 - returncode if returncode < 0 else returncode


def _fail(ranks, bad, why, detail, grace):
    # the reader thread may still be appending (a wedged-but-chatty
    # rank): give it a moment to drain, then snapshot with a retry —
    # a concurrent deque append mid-iteration raises RuntimeError,
    # and the diagnostic path must never crash the supervisor
    bad.reader.join(timeout=1.0)
    last = None
    for _ in range(5):
        try:
            last = "".join(bad.tail)
            break
        except RuntimeError:
            time.sleep(0.05)
    if last is None:
        last = "(output still streaming)\n"
    last = last or "(no output captured)\n"
    print(f"[launch] rank {bad.rank} {detail}; killing the remaining "
          f"ranks.\n[launch] rank {bad.rank} last output:\n"
          + "".join(f"  | {line}" for line in
                    last.splitlines(keepends=True)),
          file=sys.stderr, flush=True)
    # the event carries a STABLE why code (telemetry_report's
    # failure-cause section buckets on it); the measured details stay
    # in their own field + the printed diagnostic
    _emit("worker_dead", rank=bad.rank, why=why, detail=detail,
          returncode=bad.proc.returncode)
    _kill_all(ranks, grace)
    return _exit_code(bad.proc.returncode)


def _supervise(ranks, heartbeat_timeout, grace):
    """Watch rank processes and heartbeats until everyone exits zero,
    one rank fails, or a beating rank goes silent.  Returns
    ``(exit_code, failure)`` — failure is ``{"rank", "why"}`` for a
    restartable rank death, None for a clean run or an operator
    signal (signals must never be 'recovered' by a restart)."""
    stop = {"sig": None}

    def _on_signal(signum, _frame):
        stop["sig"] = signum

    old = {}
    for signum in (signal.SIGINT, signal.SIGTERM):
        old[signum] = signal.signal(signum, _on_signal)
    try:
        pending = list(ranks)
        while pending:
            if stop["sig"] is not None:
                print(f"[launch] received signal {stop['sig']}; "
                      "killing all ranks", file=sys.stderr, flush=True)
                _kill_all(ranks, grace)
                return 128 + stop["sig"], None
            for r in list(pending):
                rc = r.proc.poll()
                if rc is not None:
                    if rc != 0:
                        sig = -rc if rc < 0 else None
                        detail = (f"died with signal {sig}" if sig
                                  else f"exited with code {rc}")
                        why = "died_signal" if sig else "exited_nonzero"
                        code = _fail(ranks, r, why, detail, grace)
                        return code, {"rank": r.rank, "why": why}
                    pending.remove(r)
                    continue
                if heartbeat_timeout:
                    age = r.heartbeat_age()
                    if age is not None and age > heartbeat_timeout:
                        _fail(ranks, r, "heartbeat_silent",
                              f"heartbeat silent for {age:.1f}s "
                              f"(--heartbeat-timeout {heartbeat_timeout}"
                              "s): wedged or livelocked", grace)
                        return 1, {"rank": r.rank,
                                   "why": "heartbeat_silent"}
            time.sleep(0.1)
        return 0, None
    finally:
        for signum, handler in old.items():
            signal.signal(signum, handler)
        for r in ranks:
            r.reader.join(timeout=2.0)


def _run_pod(args, command, restart_count):
    """Spawn + supervise one generation of the pod (a fresh coordinator
    port per generation — the previous one may still be in TIME_WAIT)."""
    coordinator = f"127.0.0.1:{_free_port()}"
    hb_dir = tempfile.mkdtemp(prefix="mxnet_launch_hb_")
    ranks = []
    try:
        for rank in range(args.num_workers):
            env = _rank_env(args, coordinator, rank)
            # the beat filename carries the restart GENERATION: even if
            # a beat directory were ever reused across generations, a
            # stale file from generation g-1 can never satisfy
            # generation g's liveness check — the supervisor only
            # watches gen{restart_count} paths
            hb_path = os.path.join(
                hb_dir, f"rank{rank}.gen{restart_count}.hb")
            env["MXNET_HEARTBEAT_FILE"] = hb_path
            env["MXNET_HEARTBEAT_INTERVAL"] = str(
                args.heartbeat_interval)
            env["MXNET_RESTART_COUNT"] = str(restart_count)
            if args.checkpoint_dir:
                env["MXNET_CHECKPOINT_DIR"] = args.checkpoint_dir
            if getattr(args, "elastic", False):
                env["MXNET_ELASTIC"] = "1"
            if getattr(args, "telemetry_dir", None):
                # one recording PER RANK (append-mode across
                # generations): `tools/telemetry_report.py --pod DIR`
                # merges them by the events' rank tags
                env["MXNET_TELEMETRY_JSONL"] = os.path.join(
                    args.telemetry_dir, f"rank{rank}.jsonl")
            # piped stdout makes python ranks BLOCK-buffered: without
            # this, a hard-killed rank takes its last ~8KB of output
            # to the grave and the post-mortem tail prints stale lines
            env["PYTHONUNBUFFERED"] = "1"
            proc = subprocess.Popen(command, env=env,
                                    stdout=subprocess.PIPE,
                                    stderr=subprocess.STDOUT,
                                    text=True, errors="replace")
            ranks.append(_Rank(rank, proc, hb_path))
        return _supervise(ranks, args.heartbeat_timeout,
                          args.kill_grace)
    finally:
        _kill_all(ranks, grace=0.0)   # no-op when all reaped already
        shutil.rmtree(hb_dir, ignore_errors=True)


def launch_local(args, command):
    if args.dry_run:
        coordinator = f"127.0.0.1:{_free_port()}"
        for rank in range(args.num_workers):
            env = _rank_env(args, coordinator, rank)
            kv = " ".join(f"{k}={env[k]}" for k in sorted(env)
                          if k.startswith(("MXNET_", "DMLC")))
            print(f"[rank {rank}] {kv} {' '.join(command)}")
        return 0
    restarts_used = {}   # (rank, why) -> restarts consumed
    total_restarts = 0
    while True:
        code, fail = _run_pod(args, command, total_restarts)
        if code == 0 or fail is None or args.restarts <= 0:
            return code
        sig = (fail.get("rank"), fail.get("why"))
        used = restarts_used.get(sig, 0)
        if used >= args.restarts:
            print(f"[launch] restart budget exhausted: rank {sig[0]} "
                  f"failed the same way ({sig[1]}) {used + 1} times "
                  f"with --restarts {args.restarts}; giving up",
                  file=sys.stderr, flush=True)
            return code
        restarts_used[sig] = used + 1
        total_restarts += 1
        backoff = args.restart_backoff * (2 ** used)
        shrink = ""
        if getattr(args, "elastic", False) and args.num_workers > 1:
            # elastic recovery: re-form the pod SMALLER instead of
            # restart-at-same-size — the survivors respawn as a fresh
            # contiguous rank set 0..N-2 on a fresh coordinator, and
            # rank code re-buckets its data cursor / optimizer state
            # across the changed dp extent on restore
            args.num_workers -= 1
            shrink = (f"; elastic: re-forming on {args.num_workers} "
                      "rank(s)")
        print(f"[launch] rank {sig[0]} {sig[1]}: restarting the pod "
              f"(restart {total_restarts}; attempt {used + 1}/"
              f"{args.restarts} for this failure) after {backoff:.1f}s "
              "backoff; ranks resume from the newest complete "
              "checkpoint" +
              (f" in {args.checkpoint_dir}" if args.checkpoint_dir
               else "") + shrink,
              file=sys.stderr, flush=True)
        _emit("pod_restart", restart=total_restarts, rank=sig[0],
              why=sig[1], attempt=used + 1, budget=args.restarts,
              backoff_s=backoff, workers=args.num_workers,
              elastic=bool(getattr(args, "elastic", False)))
        time.sleep(backoff)


def launch_ssh(args, command):
    with open(args.hostfile) as f:
        hosts = [h.strip() for h in f if h.strip() and not h.startswith("#")]
    if len(hosts) < args.num_workers:
        print(f"hostfile has {len(hosts)} hosts < -n {args.num_workers}",
              file=sys.stderr)
        return 1
    coordinator = f"{hosts[0]}:{args.port or _free_port()}"
    procs = []
    for rank in range(args.num_workers):
        env = _rank_env(args, coordinator, rank)
        exports = " ".join(
            f"{k}={shlex.quote(env[k])}" for k in sorted(env)
            if k.startswith(("MXNET_", "DMLC")))
        remote_cmd = f"cd {shlex.quote(os.getcwd())} && env {exports} " + \
            " ".join(shlex.quote(c) for c in command)
        full = ["ssh", "-o", "StrictHostKeyChecking=no", hosts[rank],
                remote_cmd]
        if args.dry_run:
            print(f"[rank {rank}] {' '.join(full)}")
            continue
        procs.append(subprocess.Popen(full))
    if args.dry_run:
        return 0
    # ssh mode has no heartbeat channel (the beat files are remote);
    # supervise on exit codes alone, with the same first-failure
    # fail-fast + hardened SIGTERM -> SIGKILL teardown as local mode
    code = 0
    pending = list(procs)
    try:
        while pending:
            for p in list(pending):
                rc = p.poll()
                if rc is None:
                    continue
                pending.remove(p)
                if rc != 0 and code == 0:
                    code = _exit_code(rc)
                    rank = procs.index(p)
                    print(f"[launch] rank {rank} failed "
                          f"(exit {rc}); killing the remaining ranks",
                          file=sys.stderr, flush=True)
                    _kill_all(pending, args.kill_grace)
            time.sleep(0.1)
    except KeyboardInterrupt:
        code = 130
    _kill_all(procs, args.kill_grace if code else 0.0)
    return code


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="Launch a distributed mxnet_tpu job "
                    "(reference tools/launch.py workalike)")
    parser.add_argument("-n", "--num-workers", type=int, required=True,
                        help="number of worker processes (one per host)")
    parser.add_argument("-s", "--num-servers", type=int, default=0,
                        help="[deprecated] PS server count; servers are "
                             "no-ops on TPU (XLA collectives)")
    parser.add_argument("--launcher", choices=["local", "ssh"],
                        default="local")
    parser.add_argument("-H", "--hostfile", default=None,
                        help="hostfile for --launcher ssh")
    parser.add_argument("--port", type=int, default=None,
                        help="coordinator port (ssh mode)")
    parser.add_argument("--heartbeat-timeout", type=float, default=60.0,
                        help="seconds a rank's heartbeat may go silent "
                             "before the job is torn down (0 disables; "
                             "only enforced once a rank has started "
                             "beating, so non-mxnet commands are "
                             "unaffected)")
    parser.add_argument("--heartbeat-interval", type=float, default=1.0,
                        help="seconds between rank heartbeats "
                             "(MXNET_HEARTBEAT_INTERVAL for the ranks)")
    parser.add_argument("--kill-grace", type=float, default=5.0,
                        help="seconds between SIGTERM and SIGKILL when "
                             "tearing down surviving ranks")
    parser.add_argument("--restarts", type=int, default=0,
                        help="supervised-restart budget PER DISTINCT "
                             "failure (rank, why): on a dead/silent "
                             "rank the whole pod is torn down and "
                             "re-spawned (doubling backoff), ranks "
                             "resuming from the newest complete "
                             "checkpoint; 0 (default) = fail fast. "
                             "Local mode only")
    parser.add_argument("--restart-backoff", type=float, default=1.0,
                        help="base seconds between teardown and "
                             "re-spawn; doubles per consecutive "
                             "restart of the same failure")
    parser.add_argument("--checkpoint-dir", default=None,
                        help="exported to every rank as "
                             "MXNET_CHECKPOINT_DIR — where "
                             "mx.checkpoint auto-resume looks for the "
                             "newest complete checkpoint on restart")
    parser.add_argument("--elastic", action="store_true",
                        help="on a restartable failure re-form the pod "
                             "on ONE FEWER rank instead of the same "
                             "size (the survivor set respawns as ranks "
                             "0..N-2 with a recomputed coordinator); "
                             "ranks see MXNET_ELASTIC=1 and re-bucket "
                             "their data cursor across the changed dp "
                             "extent on restore. Requires --restarts; "
                             "local mode only")
    parser.add_argument("--telemetry-dir", default=None,
                        help="directory for per-rank telemetry "
                             "recordings: each rank gets "
                             "MXNET_TELEMETRY_JSONL=DIR/rank<r>.jsonl "
                             "(append mode across restarts); merge "
                             "with tools/telemetry_report.py --pod DIR")
    parser.add_argument("--dry-run", action="store_true",
                        help="print the per-rank commands without running")
    parser.add_argument("command", nargs=argparse.REMAINDER,
                        help="training command")
    args = parser.parse_args(argv)
    if not args.command:
        parser.error("missing training command")
    if args.num_servers:
        print("note: -s/--num-servers is a no-op on TPU (parameter-server "
              "roles are subsumed by XLA collectives)", file=sys.stderr)
    if args.heartbeat_timeout and \
            args.heartbeat_timeout <= 2 * args.heartbeat_interval:
        parser.error(
            f"--heartbeat-timeout {args.heartbeat_timeout} must exceed "
            f"2x --heartbeat-interval {args.heartbeat_interval} — a "
            "healthy rank beating on schedule would be declared silent")
    if args.restarts < 0:
        parser.error("--restarts must be >= 0")
    if args.restart_backoff < 0:
        parser.error("--restart-backoff must be >= 0")
    if args.elastic and args.restarts < 1:
        parser.error("--elastic shrinks the pod on a supervised "
                     "restart, so it requires --restarts >= 1")
    if args.telemetry_dir:
        os.makedirs(args.telemetry_dir, exist_ok=True)
        # the supervisor's own events (worker_dead, pod_restart) join
        # the per-rank recordings so `telemetry_report --pod` sees the
        # whole story; per-rank files override this in the child env
        os.environ.setdefault(
            "MXNET_TELEMETRY_JSONL",
            os.path.join(args.telemetry_dir, "launcher.jsonl"))
    if args.launcher == "ssh":
        if not args.hostfile:
            parser.error("--launcher ssh requires -H/--hostfile")
        if args.restarts:
            parser.error("--restarts is supported in local mode only "
                         "(ssh mode has no heartbeat channel to judge "
                         "restartable failures)")
        return launch_ssh(args, args.command)
    return launch_local(args, args.command)


if __name__ == "__main__":
    sys.exit(main())
