#!/usr/bin/env python3
"""Offline per-step memory budget report from a telemetry JSONL
(``MXNET_TELEMETRY_JSONL`` recorded under ``MXNET_TELEMETRY_MEM=1``).

    python tools/memory_report.py run.jsonl
    python tools/memory_report.py run.jsonl --hbm 16G
    python tools/memory_report.py run.jsonl --json
    python tools/memory_report.py --smoke

Sections (each skipped when the stream has no events of that kind):

- **per-executable memory** — per compile site: executables analyzed,
  max argument / output / temp (XLA scratch) / generated-code / peak
  bytes from the ``mem_*`` compile-event fields.
- **resident subsystems** — the live-accountant timeline
  (``device_memory`` events): last-known bytes per subsystem per
  device (``train.params`` / ``train.opt_states`` /
  ``train.grad_accum`` / ``serve.kv_pool`` / ``data.prefetch_ring``).
  The paged serve pool (ISSUE 16) meters through the same
  ``serve.kv_pool`` entry — page churn recycles fixed buffers, so the
  accounted bytes move only at init/growth and the ``--hbm`` verdict
  shape is unchanged; per-request page occupancy lives in the serve
  stream (``serve_stats.pages_in_use``, checked by
  ``telemetry_report --check-serve``).
- **budget table** — the per-step answer: PEAK resident subsystem
  totals over the recording (a pool or trainer released before the
  recording ended still had to fit while live) + the largest
  executable's temp and generated-code scratch = the HBM a step of
  this recorded config needs.  With ``--hbm N`` (bytes; K/M/G
  suffixes) the verdict "will this config fit an N-byte chip" is
  printed and the exit status is 1 when it does not — or when the
  stream carries no memory telemetry at all (an unmeasured recording
  must fail a CI gate, not sail through at 0 bytes) — the offline
  capacity check the serve runtime enforces live through
  ``MXNET_SERVE_HBM_BUDGET``.

``--smoke`` records its own tiny workload (a fused train step + a
slot-pool decode server on a toy GPT) under ``MXNET_TELEMETRY_MEM=1``,
then asserts the report pipeline end to end: memory fields from both
train and serve compile sites, accountant events for ``train.params``
and ``serve.kv_pool``, and a fits-verdict round trip.  Tier-1 shells it
(tests/test_memory.py).

This reader is dependency-free on purpose (no mxnet_tpu/jax import
unless ``--smoke`` runs a workload) so a recording can be analyzed
anywhere.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
from collections import defaultdict

_SUFFIXES = {"k": 1 << 10, "m": 1 << 20, "g": 1 << 30, "t": 1 << 40}

# ledger subsystems rendered in budget order (anything else the stream
# carries is appended after these)
_KNOWN_SUBSYSTEMS = ("train.params", "train.opt_states",
                     "train.grad_accum", "serve.kv_pool",
                     "data.prefetch_ring")


def parse_bytes(raw):
    """Local copy of ``telemetry.memory.parse_bytes`` (this tool stays
    importable without mxnet_tpu/jax for offline analysis) — same
    validation: clean ``ValueError`` on junk, negatives rejected."""
    s = str(raw).strip()
    mult = 1
    if s and s[-1].lower() in _SUFFIXES:
        mult = _SUFFIXES[s[-1].lower()]
        s = s[:-1]
    try:
        n = int(float(s) * mult)
    except (ValueError, OverflowError):
        raise ValueError(
            f"expected bytes (int, optionally with a K/M/G/T suffix), "
            f"got {raw!r}") from None
    if n < 0:
        raise ValueError(f"bytes must be >= 0, got {raw!r}")
    return n


def fmt_bytes(n):
    n = int(n)
    for unit, div in (("GiB", 1 << 30), ("MiB", 1 << 20),
                      ("KiB", 1 << 10)):
        if abs(n) >= div:
            return f"{n / div:.2f} {unit}"
    return f"{n} B"


def load(path):
    events = []
    with open(path, "r", encoding="utf-8") as fh:
        for i, line in enumerate(fh, start=1):
            line = line.strip()
            if not line:
                continue
            try:
                ev = json.loads(line)
            except json.JSONDecodeError as e:
                print(f"# {path}:{i}: skipping unparseable line ({e})",
                      file=sys.stderr)
                continue
            if isinstance(ev, dict):
                events.append(ev)
    return events


# --------------------------------------------------------------------- #
# sections
# --------------------------------------------------------------------- #

def compile_memory(events):
    """Per-site rows over compile events that carry ``mem_*`` fields."""
    by_site = defaultdict(list)
    for e in events:
        if e.get("kind") == "compile" and "mem_peak_bytes" in e:
            by_site[e.get("site", "?")].append(e)
    rows = []
    for site in sorted(by_site):
        evs = by_site[site]
        rows.append({
            "site": site,
            "executables": len(evs),
            "arg_bytes": max(e.get("mem_arg_bytes", 0) for e in evs),
            "out_bytes": max(e.get("mem_out_bytes", 0) for e in evs),
            "temp_bytes": max(e.get("mem_temp_bytes", 0) for e in evs),
            "code_bytes": max(e.get("mem_code_bytes", 0) for e in evs),
            "peak_bytes": max(e.get("mem_peak_bytes", 0) for e in evs),
        })
    return rows


def subsystem_memory(events, agg="last"):
    """Accountant bytes per ``(subsystem, device)`` from the
    ``device_memory`` timeline.  ``agg="last"`` is the end-of-recording
    view (dropped entries report 0 — the "resident subsystems"
    display); ``agg="peak"`` keeps each entry's maximum, which is what
    the fit verdict must use — a server closed before the recording
    ends emits a final 0 for its KV pool, but the step still had to
    fit while the pool was live."""
    seen = {}            # (subsystem, key, device) -> bytes
    for e in events:
        if e.get("kind") != "device_memory":
            continue
        k = (e.get("subsystem", "?"), e.get("key", "?"),
             e.get("device", "?"))
        b = e.get("bytes", 0)
        seen[k] = max(seen.get(k, 0), b) if agg == "peak" else b
    out = defaultdict(lambda: defaultdict(int))
    for (sub, _key, dev), b in seen.items():
        out[sub][dev] += b
    return {sub: dict(devs) for sub, devs in out.items()}


def budget_table(events):
    """The per-step budget rows: PEAK resident subsystem totals over
    the recording (summed over devices — single-chip reading;
    per-device splits are in :func:`subsystem_memory`) plus the
    largest executable's temp and generated-code scratch.  Peak, not
    last-known: a pool/trainer released before the sink detached still
    had to fit while it was live."""
    subs = subsystem_memory(events, agg="peak")
    comp = compile_memory(events)
    rows = []
    ordered = [s for s in _KNOWN_SUBSYSTEMS if s in subs] + \
        sorted(s for s in subs if s not in _KNOWN_SUBSYSTEMS)
    for sub in ordered:
        rows.append({"item": sub, "kind": "resident",
                     "bytes": sum(subs[sub].values())})
    if comp:
        temp = max(r["temp_bytes"] for r in comp)
        code = max(r["code_bytes"] for r in comp)
        worst = max(comp, key=lambda r: r["temp_bytes"])
        rows.append({"item": f"xla temp (max: {worst['site']})",
                     "kind": "scratch", "bytes": temp})
        if code:
            rows.append({"item": "generated code (max)",
                         "kind": "scratch", "bytes": code})
    rows.append({"item": "TOTAL (resident + worst-step scratch)",
                 "kind": "total",
                 "bytes": sum(r["bytes"] for r in rows)})
    return rows


def fit_verdict(events, hbm_bytes):
    """Fit verdict for an ``hbm_bytes`` chip.  ``measured`` requires
    per-executable ``mem_*`` compile events in the stream — the
    always-on accountant alone cannot answer "does a STEP fit": a
    recording made without ``MXNET_TELEMETRY_MEM=1`` has resident rows
    but zero bytes of XLA scratch, and passing that through a CI gate
    would bless a config whose executable temp OOMs the real chip."""
    rows = budget_table(events)
    total = rows[-1]["bytes"]
    measured = bool(compile_memory(events))
    return {
        "hbm_bytes": hbm_bytes,
        "total_bytes": total,
        "headroom_bytes": hbm_bytes - total,
        "measured": measured,
        "fits": measured and total <= hbm_bytes,
    }


def render(events):
    lines = []
    comp = compile_memory(events)
    if comp:
        lines.append("per-executable memory (max over compiles, "
                     "MXNET_TELEMETRY_MEM=1 fields)")
        lines.append(f"  {'site':<24}{'execs':>6}{'args':>12}"
                     f"{'outputs':>12}{'temp':>12}{'peak':>12}")
        for r in comp:
            lines.append(
                f"  {r['site']:<24}{r['executables']:>6}"
                f"{fmt_bytes(r['arg_bytes']):>12}"
                f"{fmt_bytes(r['out_bytes']):>12}"
                f"{fmt_bytes(r['temp_bytes']):>12}"
                f"{fmt_bytes(r['peak_bytes']):>12}")
    subs = subsystem_memory(events)
    if subs:
        lines.append("")
        lines.append("resident subsystems (accountant, last known)")
        for sub in sorted(subs):
            for dev, b in sorted(subs[sub].items()):
                lines.append(f"  {sub:<24}{dev:<12}{fmt_bytes(b):>12}")
    table = budget_table(events)
    if len(table) > 1:
        lines.append("")
        lines.append("per-step budget")
        for r in table:
            lines.append(f"  {r['item']:<44}{fmt_bytes(r['bytes']):>12}")
    if not lines:
        lines.append("(no memory telemetry in the stream — record with "
                     "MXNET_TELEMETRY_MEM=1 and MXNET_TELEMETRY_JSONL)")
    return "\n".join(lines)


# --------------------------------------------------------------------- #
# smoke
# --------------------------------------------------------------------- #

def smoke():
    """Record a tiny train + serve workload under
    ``MXNET_TELEMETRY_MEM=1`` and assert the whole report pipeline:
    memory fields from train AND serve compile sites, accountant events
    for params and the KV pool, a fits verdict round trip."""
    import tempfile

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ["MXNET_TELEMETRY_MEM"] = "1"
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import numpy as onp

    import mxnet_tpu as mx
    from mxnet_tpu import gluon, telemetry
    from mxnet_tpu.gluon import nn
    from mxnet_tpu.models import GPT, GPTConfig
    from mxnet_tpu.serve import DecodeServer

    jsonl = os.path.join(tempfile.mkdtemp(prefix="mxtpu_memrep_"),
                         "mem.jsonl")
    sink = telemetry.add_jsonl_sink(jsonl)
    try:
        # -- fused train step (train.params / opt_states ledger +
        #    gluon.fused_step compile memory)
        mx.random.seed(0)
        net = nn.Dense(8, in_units=8)
        net.initialize(mx.init.Xavier())
        trainer = gluon.Trainer(net.collect_params(), "adamw",
                                {"learning_rate": 1e-3}, kvstore=None)
        loss_l = gluon.loss.L2Loss()

        def loss_fn(xx, yy):
            return loss_l(net(xx), yy)

        rng = onp.random.RandomState(0)
        x = mx.nd.array(rng.rand(4, 8).astype("float32"))
        y = mx.nd.array(rng.rand(4, 8).astype("float32"))
        trainer.fused_step(loss_fn, x, y)

        # -- slot-pool decode server (serve.kv_pool ledger +
        #    serve.step/serve.admit compile memory)
        gpt = GPT(GPTConfig(vocab_size=64, max_length=24, num_layers=2,
                            units=16, num_heads=2, hidden_size=32))
        gpt.initialize(mx.init.Normal(0.02))
        srv = DecodeServer(gpt, max_total_len=24, pool_sizes=(2,),
                           autostart=False)
        streams = [srv.submit(rng.randint(0, 64, (4,)),
                              max_new_tokens=4) for _ in range(2)]
        while srv.pump():
            pass
        for s in streams:
            s.tokens(30)
        srv.close()
    finally:
        telemetry.remove_sink(sink)

    # -- ISSUE 18: the same pool geometry recorded twice, f32 pages vs
    #    int8 (codes + scales) pages — at a budget between the two
    #    totals the verdict flips "does not fit" -> "fits", which is
    #    the capacity claim of quantized KV pages stated by the same
    #    accountant bytes the report prices
    kv_events = {}
    for kv_dtype in ("native", "int8"):
        j2 = os.path.join(tempfile.mkdtemp(prefix="mxtpu_memrep_"),
                          f"kv_{kv_dtype}.jsonl")
        sink2 = telemetry.add_jsonl_sink(j2)
        try:
            srv = DecodeServer(gpt, max_total_len=24, pool_sizes=(2,),
                               kv_dtype=kv_dtype, autostart=False)
            s = srv.submit(rng.randint(0, 64, (4,)), max_new_tokens=4)
            while srv.pump():
                pass
            s.tokens(30)
            srv.close()
        finally:
            telemetry.remove_sink(sink2)
        kv_events[kv_dtype] = load(j2)
    t_f32 = fit_verdict(kv_events["native"], 1)["total_bytes"]
    t_i8 = fit_verdict(kv_events["int8"], 1)["total_bytes"]
    assert t_i8 < t_f32, (t_i8, t_f32)
    mid = (t_i8 + t_f32) // 2
    assert not fit_verdict(kv_events["native"], mid)["fits"]
    assert fit_verdict(kv_events["int8"], mid)["fits"]

    events = load(jsonl)
    comp = compile_memory(events)
    sites = {r["site"] for r in comp}
    assert {"gluon.fused_step", "serve.step"} <= sites, sites
    subs = subsystem_memory(events)
    assert "train.params" in subs and "serve.kv_pool" in subs, subs
    # the server closed before the sink detached, so last-known pool
    # bytes are 0 — but the PEAK view (what the fit verdict uses) must
    # carry the live pool's size
    peak = subsystem_memory(events, agg="peak")
    assert sum(peak["serve.kv_pool"].values()) > 0, peak
    print(render(events))
    verdict = fit_verdict(events, parse_bytes("16G"))
    assert verdict["fits"], verdict
    bad = fit_verdict(events, 1024)
    assert not bad["fits"], bad
    print(f"\nmemory report smoke OK: {len(comp)} analyzed sites "
          f"({', '.join(sorted(sites))}), "
          f"{len(subs)} resident subsystems, "
          f"total {fmt_bytes(verdict['total_bytes'])} "
          f"fits 16G with {fmt_bytes(verdict['headroom_bytes'])} "
          "headroom; int8 KV pages flip the verdict at "
          f"{fmt_bytes(mid)} ({fmt_bytes(t_f32)} f32 does not fit, "
          f"{fmt_bytes(t_i8)} int8 fits)")
    return 0


# --------------------------------------------------------------------- #

def main(argv=None):
    ap = argparse.ArgumentParser(
        description="Per-step memory budget report from a telemetry "
                    "JSONL recorded under MXNET_TELEMETRY_MEM=1.")
    ap.add_argument("path", nargs="?",
                    help="JSONL recorded via MXNET_TELEMETRY_JSONL")
    ap.add_argument("--hbm", metavar="BYTES",
                    help="chip HBM to check against (K/M/G suffixes; "
                         "e.g. 16G for a v5e chip); exit 1 when the "
                         "recorded config does not fit")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable summary instead of tables")
    ap.add_argument("--smoke", action="store_true",
                    help="record + report a tiny train/serve workload "
                         "end to end (tier-1 gate, CPU)")
    args = ap.parse_args(argv)

    if args.smoke:
        return smoke()
    if args.path is None:
        ap.error("path is required unless --smoke")

    events = load(args.path)
    verdict = None
    if args.hbm is not None:
        try:
            hbm = parse_bytes(args.hbm)
        except ValueError as e:
            ap.error(f"--hbm: {e}")
        verdict = fit_verdict(events, hbm)
    if args.json:
        print(json.dumps({
            "events": len(events),
            "compile_memory": compile_memory(events),
            "subsystems": subsystem_memory(events),
            "budget": budget_table(events),
            "verdict": verdict,
        }, indent=2, sort_keys=True))
    else:
        print(f"# {args.path}: {len(events)} events")
        print(render(events))
        if verdict is not None:
            if not verdict["measured"]:
                print("\nNO MEMORY TELEMETRY: the stream has no "
                      "per-executable mem_* compile events, so the "
                      "step's XLA scratch is unknown — cannot judge "
                      "the fit (record with MXNET_TELEMETRY_MEM=1 "
                      "and MXNET_TELEMETRY_JSONL)")
            else:
                word = "FITS" if verdict["fits"] else "DOES NOT FIT"
                print(f"\n{word} {fmt_bytes(verdict['hbm_bytes'])}: "
                      f"total {fmt_bytes(verdict['total_bytes'])}, "
                      f"headroom "
                      f"{fmt_bytes(verdict['headroom_bytes'])}")
    return 0 if verdict is None or verdict["fits"] else 1


if __name__ == "__main__":
    sys.exit(main())
