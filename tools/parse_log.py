#!/usr/bin/env python3
"""Parse training logs into a metric table (reference ``tools/parse_log.py``:
scrapes accuracy/speed from fit-loop logs).

Understands the Module/Estimator log shapes::

    Epoch[3] Train-accuracy=0.83
    Epoch[3] Validation-accuracy=0.81
    Epoch[3] Time cost=12.3
    Epoch[3] Batch [20]	Speed: 493.81 samples/sec

Usage: ``python tools/parse_log.py train.log [--format csv|md]``
"""
from __future__ import annotations

import argparse
import re
import sys
from collections import defaultdict

_PATTERNS = {
    "train": re.compile(r"Epoch\[(\d+)\].*Train-([\w-]+)=([\d.eE+-]+)"),
    "val": re.compile(r"Epoch\[(\d+)\].*Validation-([\w-]+)=([\d.eE+-]+)"),
    "time": re.compile(r"Epoch\[(\d+)\].*Time cost=([\d.eE+-]+)"),
    "speed": re.compile(r"Epoch\[(\d+)\].*Speed[:=]\s*([\d.eE+-]+)"),
}


def parse(lines):
    rows = defaultdict(dict)
    for line in lines:
        m = _PATTERNS["train"].search(line)
        if m:
            rows[int(m.group(1))][f"train-{m.group(2)}"] = float(m.group(3))
            continue
        m = _PATTERNS["val"].search(line)
        if m:
            rows[int(m.group(1))][f"val-{m.group(2)}"] = float(m.group(3))
            continue
        m = _PATTERNS["time"].search(line)
        if m:
            rows[int(m.group(1))]["time"] = float(m.group(2))
            continue
        m = _PATTERNS["speed"].search(line)
        if m:
            e = int(m.group(1))
            rows[e].setdefault("_speeds", []).append(float(m.group(2)))
    out = []
    for epoch in sorted(rows):
        r = dict(rows[epoch])
        speeds = r.pop("_speeds", None)
        if speeds:
            r["speed"] = sum(speeds) / len(speeds)
        out.append({"epoch": epoch, **r})
    return out


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("logfile")
    p.add_argument("--format", choices=["csv", "md"], default="md")
    args = p.parse_args(argv)
    with open(args.logfile) as f:
        rows = parse(f)
    if not rows:
        print("no metrics found", file=sys.stderr)
        return 1
    cols = ["epoch"] + sorted({k for r in rows for k in r} - {"epoch"})
    if args.format == "csv":
        print(",".join(cols))
        for r in rows:
            print(",".join(str(r.get(c, "")) for c in cols))
    else:
        print("| " + " | ".join(cols) + " |")
        print("|" + "---|" * len(cols))
        for r in rows:
            print("| " + " | ".join(str(r.get(c, "")) for c in cols) + " |")
    return 0


if __name__ == "__main__":
    sys.exit(main())
