#!/usr/bin/env python3
"""Allreduce bandwidth measurement (reference ``tools/bandwidth/measure.py``:
kvstore push/pull bandwidth across devices).

Measures the kvstore pushpull path (data-parallel gradient allreduce) for a
range of tensor sizes; on one chip the reduce is local (measures dispatch +
memory), on a mesh it exercises ICI collectives via the parallel package.

Usage: ``python tools/bandwidth/measure.py [--kvstore local] [--sizes ...]``
"""
from __future__ import annotations

import argparse
import os
import sys
import time

import numpy as onp

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))


def measure_kvstore(kv_type, sizes, repeats):
    import mxnet_tpu as mx
    kv = mx.kv.create(kv_type)
    rows = []
    for size in sizes:
        n = size // 4  # fp32 elements
        val = mx.nd.array(onp.random.rand(n).astype(onp.float32))
        out = mx.nd.zeros(n)
        kv.init(size, val)
        kv.pushpull(size, val, out=out)  # warmup
        out.asnumpy()
        t0 = time.perf_counter()
        for _ in range(repeats):
            kv.pushpull(size, val, out=out)
        out.asnumpy()
        dt = (time.perf_counter() - t0) / repeats
        rows.append({"bytes": size, "ms": dt * 1e3,
                     "GB/s": size / dt / 1e9})
    return rows


def measure_collective(sizes, repeats):
    """all_reduce over the device mesh (the ICI path)."""
    import jax
    import mxnet_tpu as mx
    from mxnet_tpu import parallel
    ndev = len(jax.devices())
    mesh = parallel.make_mesh({"dp": ndev})
    rows = []
    for size in sizes:
        n = size // 4
        val = mx.nd.array(onp.random.rand(n).astype(onp.float32))
        out = parallel.all_reduce(val, mesh=mesh, axis="dp")
        out.asnumpy()
        t0 = time.perf_counter()
        for _ in range(repeats):
            out = parallel.all_reduce(val, mesh=mesh, axis="dp")
        out.asnumpy()
        dt = (time.perf_counter() - t0) / repeats
        # ring allreduce moves 2*(n-1)/n of the buffer per link
        rows.append({"bytes": size, "ms": dt * 1e3,
                     "algo GB/s": size / dt / 1e9 * 2 * (ndev - 1) /
                     max(ndev, 1)})
    return rows


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--kvstore", default="local")
    p.add_argument("--collective", action="store_true",
                   help="measure mesh all_reduce instead of kvstore")
    p.add_argument("--sizes", type=int, nargs="*",
                   default=[1 << 16, 1 << 20, 1 << 24])
    p.add_argument("--repeats", type=int, default=10)
    args = p.parse_args(argv)
    rows = measure_collective(args.sizes, args.repeats) if args.collective \
        else measure_kvstore(args.kvstore, args.sizes, args.repeats)
    keys = list(rows[0].keys())
    print("".join(f"{k:>14}" for k in keys))
    for r in rows:
        print("".join(f"{r[k]:>14.3f}" if isinstance(r[k], float)
                      else f"{r[k]:>14}" for k in keys))
    return 0


if __name__ == "__main__":
    sys.exit(main())
