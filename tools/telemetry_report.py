#!/usr/bin/env python3
"""Summarize a telemetry JSONL (``MXNET_TELEMETRY_JSONL`` /
``mx.telemetry.add_jsonl_sink``) into the BASELINE.md-style tables, and
re-check the dispatch/retrace invariants from the recorded stream alone.

    python tools/telemetry_report.py run.jsonl
    python tools/telemetry_report.py run.jsonl --check-serve
    python tools/telemetry_report.py run.jsonl --json

Sections (each skipped when the file has no events of that kind):

- **compile events** — per site: count, retraces, total/max wall time,
  HLO op count range (when recorded under ``MXNET_TELEMETRY_HLO=1``).
- **serve requests** — per server: request count by retirement reason,
  token totals, p50/p99 TTFT and queue wait, admission wave stats.
- **serve stats** — the per-server close() snapshot: steps, dispatch
  counters, occupancy.
- **failure causes** — the fault-tolerance events (ISSUE 13):
  ``worker_dead`` / ``deadline_exceeded`` / ``request_cancelled`` /
  ``fault_injected`` / ``watchdog_fired`` / ``kvstore_error`` /
  ``checkpoint_corrupt``, counted per kind with a
  per-site/server/reason breakdown.
- **checkpoints** — ``checkpoint_saved`` / ``checkpoint_restored``
  rollup per directory: saves, bytes, snapshot/write seconds (the
  async-save stall truth), restores and corrupt skips.
- **restarts** — ``pod_restart`` events from the
  ``tools/launch.py --restarts`` supervisor: per (rank, why) counts,
  attempts, backoff (ISSUE 15 recovery loop).
- **bench rows** — ``kind=bench`` events (serve_bench / step_profile
  measured rows) passed through as a table.

``--check-serve`` re-derives the test-pinned serving invariants from
the stream (no process state needed):

1. compile count per server ≤ the pinned ladder product
   (``len(admit_sizes) × len(prefill_buckets) × len(pool_sizes)`` from
   its ``serve_config`` event) and ≤ 1 step compile per pool size;
2. zero RETRACES: every serve compile event is a distinct program
   (first-trace), never a second signature of one;
3. one step-executable dispatch per decode step
   (``serve_stats.counters.step_dispatches == serve_stats.steps``);
4. pool bytes ≤ the configured HBM budget across the whole recording:
   for servers whose ``serve_config`` carries a non-null
   ``hbm_budget``, every ``serve.kv_pool`` accountant sample
   (``device_memory`` events) and the close-time
   ``serve_stats.pool_bytes`` must stay within it;
5. pages ≤ pool capacity (ISSUE 16): any ``serve_stats`` carrying the
   paged-pool fields must report ``pages_in_use <= pages_total``
   (streams recorded before paging simply lack the fields and skip
   the check);
6. speculative-decoding ledger (ISSUE 17): verify compiles per server
   ≤ ``len(spec_sizes) × len(pool_sizes)`` and retrace-free like the
   other serve sites, and every proposed draft token resolves —
   ``accepted + rejected == proposed`` re-derived both from the
   per-dispatch ``serve_spec`` events and from the close-time
   ``serve_stats`` draft counters (pre-speculation recordings lack
   the fields and skip the check).

Exit status 1 when a check fails (the tier-1 serve smoke shells this
against the JSONL ``benchmark/serve_bench.py --smoke`` records).
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from collections import defaultdict


def load_pod(path):
    """Merge a pod's telemetry: ``path`` is either one merged JSONL
    (events already rank-tagged by ``mxnet_tpu.telemetry.emit``) or a
    directory of per-rank recordings (``tools/launch.py
    --telemetry-dir``: ``rank<r>.jsonl``).  Returns the union sorted
    by timestamp — the rank field on each event, not the source file,
    is the attribution."""
    if os.path.isdir(path):
        files = sorted(glob.glob(os.path.join(path, "*.jsonl")))
        if not files:
            print(f"# {path}: no *.jsonl recordings in directory",
                  file=sys.stderr)
        events = []
        for f in files:
            events.extend(load(f))
        events.sort(key=lambda e: e.get("ts", 0.0))
        return events
    return load(path)


def load(path):
    events = []
    with open(path, "r", encoding="utf-8") as fh:
        for i, line in enumerate(fh, start=1):
            line = line.strip()
            if not line:
                continue
            try:
                ev = json.loads(line)
            except json.JSONDecodeError as e:
                print(f"# {path}:{i}: skipping unparseable line ({e})",
                      file=sys.stderr)
                continue
            if isinstance(ev, dict):
                events.append(ev)
    return events


def _pct(xs, q):
    xs = sorted(xs)
    if not xs:
        return None
    return xs[min(len(xs) - 1, int(q * len(xs)))]


def _ms(v):
    """Render an already-milliseconds value (None = no samples)."""
    return "-" if v is None else f"{v:.3f}"


def _to_ms(v):
    return None if v is None else round(v * 1e3, 3)


# --------------------------------------------------------------------- #
# sections
# --------------------------------------------------------------------- #

def compile_summary(events):
    """Per-site compile rows: count/retraces/wall/hlo."""
    rows = []
    by_site = defaultdict(list)
    for e in events:
        if e.get("kind") == "compile":
            by_site[e.get("site", "?")].append(e)
    for site in sorted(by_site):
        evs = by_site[site]
        walls = [e.get("wall_s", 0.0) for e in evs]
        hlo = [e["hlo_ops"] for e in evs if "hlo_ops" in e]
        rows.append({
            "site": site,
            "compiles": len(evs),
            "retraces": sum(1 for e in evs if e.get("retrace")),
            "wall_s_total": round(sum(walls), 3),
            "wall_s_max": round(max(walls), 3) if walls else 0.0,
            "hlo_ops_min": min(hlo) if hlo else None,
            "hlo_ops_max": max(hlo) if hlo else None,
        })
    return rows


def serve_summary(events):
    """Per-server request-span rows."""
    by_srv = defaultdict(list)
    for e in events:
        if e.get("kind") == "serve_request":
            by_srv[e.get("server", "?")].append(e)
    rows = []
    for srv in sorted(by_srv):
        evs = by_srv[srv]
        reasons = defaultdict(int)
        for e in evs:
            reasons[e.get("reason", "?")] += 1
        ttfts = [e["ttft_s"] for e in evs if e.get("ttft_s") is not None]
        waits = [e["queue_wait_s"] for e in evs
                 if e.get("queue_wait_s") is not None]
        waves = [e["wave"] for e in evs if e.get("wave") is not None]
        rows.append({
            "server": srv,
            "requests": len(evs),
            "reasons": dict(sorted(reasons.items())),
            "tokens": sum(e.get("tokens", 0) for e in evs),
            "p50_ttft_ms": _to_ms(_pct(ttfts, 0.5)),
            "p99_ttft_ms": _to_ms(_pct(ttfts, 0.99)),
            "p50_queue_wait_ms": _to_ms(_pct(waits, 0.5)),
            "p99_queue_wait_ms": _to_ms(_pct(waits, 0.99)),
            "mean_admit_wave": (round(sum(waves) / len(waves), 2)
                                if waves else None),
        })
    return rows


FAILURE_KINDS = ("worker_dead", "deadline_exceeded", "request_cancelled",
                 "fault_injected", "watchdog_fired", "kvstore_error",
                 "checkpoint_corrupt")


def failure_summary(events):
    """Aggregate the failure-cause events (ISSUE 13) per kind: count +
    the per-site/server/reason breakdown, so one recording answers
    "what failed, where, how often" next to the perf tables."""
    rows = []
    by_kind = defaultdict(list)
    for e in events:
        if e.get("kind") in FAILURE_KINDS:
            by_kind[e["kind"]].append(e)
    for kind in FAILURE_KINDS:
        evs = by_kind.get(kind)
        if not evs:
            continue
        detail = defaultdict(int)
        for e in evs:
            where = e.get("site") or e.get("server") or \
                (f"rank {e['rank']}" if "rank" in e else None) or \
                e.get("dir") or "?"
            what = e.get("fault_kind") or e.get("reason") or \
                e.get("why") or e.get("command") or e.get("error")
            detail[f"{where}" + (f": {what}" if what else "")] += 1
        rows.append({"kind": kind, "count": len(evs),
                     "detail": dict(sorted(detail.items()))})
    return rows


def checkpoint_summary(events):
    """Per-directory checkpoint rollup: saves (bytes + the measured
    snapshot/write stalls — the async-save acceptance truth), restores,
    and corrupt skips."""
    by_dir = defaultdict(lambda: {"saves": 0, "restores": 0,
                                  "corrupt": 0, "bytes": 0,
                                  "snapshot_s": [], "write_s": [],
                                  "last_step": None})
    saw = False
    for e in events:
        kind = e.get("kind")
        if kind not in ("checkpoint_saved", "checkpoint_restored",
                        "checkpoint_corrupt"):
            continue
        saw = True
        d = by_dir[e.get("dir", "?")]
        if kind == "checkpoint_saved":
            d["saves"] += 1
            d["bytes"] += e.get("bytes", 0)
            if e.get("snapshot_s") is not None:
                d["snapshot_s"].append(e["snapshot_s"])
            if e.get("write_s") is not None:
                d["write_s"].append(e["write_s"])
            d["last_step"] = e.get("step")
        elif kind == "checkpoint_restored":
            d["restores"] += 1
        else:
            d["corrupt"] += 1
    if not saw:
        return []
    rows = []
    for path in sorted(by_dir):
        d = by_dir[path]
        snaps, writes = d["snapshot_s"], d["write_s"]
        rows.append({
            "dir": path, "saves": d["saves"], "restores": d["restores"],
            "corrupt": d["corrupt"], "bytes": d["bytes"],
            "last_step": d["last_step"],
            "snapshot_ms_mean": _to_ms(sum(snaps) / len(snaps))
            if snaps else None,
            "snapshot_ms_max": _to_ms(max(snaps)) if snaps else None,
            "write_ms_mean": _to_ms(sum(writes) / len(writes))
            if writes else None,
        })
    return rows


def restart_summary(events):
    """``pod_restart`` rows from the launch supervisor: one recording
    answers how often the pod restarted, for which failures, and how
    much backoff it paid."""
    evs = [e for e in events if e.get("kind") == "pod_restart"]
    if not evs:
        return []
    detail = defaultdict(int)
    for e in evs:
        detail[f"rank {e.get('rank', '?')}: {e.get('why', '?')}"] += 1
    return [{"restarts": len(evs),
             "backoff_s_total": round(sum(e.get("backoff_s", 0.0)
                                          for e in evs), 3),
             "max_attempt": max(e.get("attempt", 1) for e in evs),
             "detail": dict(sorted(detail.items()))}]


def _parse_bytes(raw):
    """``14G``-style byte sizes for ``--hbm-budget``."""
    raw = str(raw).strip()
    mult = 1
    suffixes = {"K": 1 << 10, "M": 1 << 20, "G": 1 << 30, "T": 1 << 40}
    if raw and raw[-1].upper() in suffixes:
        mult = suffixes[raw[-1].upper()]
        raw = raw[:-1]
    return int(float(raw) * mult)


def pod_summary(events, hbm_budget=None):
    """Per-rank rollup of a merged pod recording — the two operator
    questions first: WHICH HOST RETRACED (rank-tagged ``compile``
    events with ``retrace``) and WHICH HOST IS OVER ITS HBM BUDGET
    (peak concurrent total of the rank's ``device_memory`` /
    ``device_bytes`` accountant gauges vs ``hbm_budget``).  Events
    without a rank tag (the launch supervisor's own ``worker_dead`` /
    ``pod_restart``) roll up under rank ``"pod"``."""
    by_rank = defaultdict(lambda: {
        "events": 0, "compiles": 0, "retraces": 0,
        "retrace_sites": set(), "compile_wall_s": 0.0,
        "peak_device_bytes": 0, "_gauges": {}, "faults": 0,
        "dist_inits": 0, "last_step": None, "saves": 0})
    for e in events:
        rank = e.get("rank", "pod")
        d = by_rank[rank]
        d["events"] += 1
        kind = e.get("kind")
        if kind == "compile":
            d["compiles"] += 1
            d["compile_wall_s"] += e.get("wall_s", 0.0)
            if e.get("retrace"):
                d["retraces"] += 1
                d["retrace_sites"].add(str(e.get("site", "?")))
        elif kind == "device_memory":
            # replay the accountant gauges in ts order: the rank's HBM
            # truth is the peak CONCURRENT total, not the max sample
            key = (e.get("subsystem", "?"), e.get("key", "?"))
            d["_gauges"][key] = e.get("bytes", 0)
            d["peak_device_bytes"] = max(
                d["peak_device_bytes"], sum(d["_gauges"].values()))
        elif kind == "fault_injected":
            d["faults"] += 1
        elif kind == "dist_init":
            d["dist_inits"] += 1
        elif kind == "checkpoint_saved":
            d["saves"] += 1
            d["last_step"] = e.get("step")
    rows = []
    for rank in sorted(by_rank, key=lambda r: (isinstance(r, str), r)):
        d = by_rank[rank]
        row = {"rank": rank, "events": d["events"],
               "compiles": d["compiles"], "retraces": d["retraces"],
               "retrace_sites": sorted(d["retrace_sites"]),
               "compile_wall_s": round(d["compile_wall_s"], 3),
               "peak_device_bytes": d["peak_device_bytes"],
               "faults": d["faults"], "dist_inits": d["dist_inits"],
               "saves": d["saves"], "last_step": d["last_step"]}
        if hbm_budget is not None and rank != "pod":
            row["over_hbm_budget"] = \
                d["peak_device_bytes"] > hbm_budget
        rows.append(row)
    return rows


def render_pod(events, hbm_budget=None):
    rows = pod_summary(events, hbm_budget)
    lines = ["pod (per rank)",
             f"  {'rank':<6}{'events':>8}{'compiles':>9}"
             f"{'retraces':>9}{'wall(s)':>9}{'peak bytes':>12}"
             f"{'saves':>7}{'last step':>10}"]
    for r in rows:
        lines.append(
            f"  {str(r['rank']):<6}{r['events']:>8}{r['compiles']:>9}"
            f"{r['retraces']:>9}{r['compile_wall_s']:>9.2f}"
            f"{r['peak_device_bytes']:>12}{r['saves']:>7}"
            f"{str(r['last_step'] if r['last_step'] is not None else '-'):>10}")
    retraced = [r for r in rows if r["retraces"]]
    if retraced:
        lines.append("  retraced hosts: " + ", ".join(
            f"rank {r['rank']} ({', '.join(r['retrace_sites'])})"
            for r in retraced))
    else:
        lines.append("  retraced hosts: none")
    if hbm_budget is not None:
        over = [r for r in rows if r.get("over_hbm_budget")]
        if over:
            lines.append(
                f"  over hbm budget ({hbm_budget} bytes): " + ", ".join(
                    f"rank {r['rank']} "
                    f"(peak {r['peak_device_bytes']})" for r in over))
        else:
            lines.append(f"  over hbm budget ({hbm_budget} bytes): "
                         "none")
    return "\n".join(lines)


def _serve_schema():
    """Load ``mxnet_tpu/serve/schema.py`` standalone, by file path —
    the operand/slot-state declarations import nothing, so this tool
    can price slot state EXACTLY without importing the package (which
    would pull jax).  Returns None when the tree isn't alongside the
    tool (e.g. the report script copied into a recording dir)."""
    import importlib.util

    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        os.pardir, "mxnet_tpu", "serve", "schema.py")
    if not os.path.exists(path):
        return None
    try:
        spec = importlib.util.spec_from_file_location(
            "_serve_operand_schema", path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod
    except Exception:
        return None


def check_serve(events):
    """Re-derive the serving invariants from the stream; returns a list
    of failure strings (empty = all good)."""
    failures = []
    configs = {e["server"]: e for e in events
               if e.get("kind") == "serve_config" and "server" in e}
    compiles = defaultdict(list)
    for e in events:
        if e.get("kind") == "compile" and \
                e.get("site") in ("serve.step", "serve.admit",
                                  "serve.verify"):
            compiles[e.get("server")].append(e)
    stats = [e for e in events if e.get("kind") == "serve_stats"]

    for srv, cfg in sorted(configs.items()):
        if cfg.get("sync_mode"):
            continue
        evs = compiles.get(srv, [])
        admits = [e for e in evs if e["site"] == "serve.admit"]
        steps = [e for e in evs if e["site"] == "serve.step"]
        verifies = [e for e in evs if e["site"] == "serve.verify"]
        ladder = (len(cfg.get("admit_sizes", [])) *
                  len(cfg.get("prefill_buckets", [])) *
                  len(cfg.get("pool_sizes", [])) or None)
        if ladder is not None and len(admits) > ladder:
            failures.append(
                f"{srv}: {len(admits)} admit compiles exceed the "
                f"pinned ladder product {ladder}")
        if len(steps) > len(cfg.get("pool_sizes", [1])):
            failures.append(
                f"{srv}: {len(steps)} step compiles for "
                f"{len(cfg['pool_sizes'])} pinned pool sizes")
        # verify programs are pinned to the spec k ladder x pool sizes
        # (accept/reject churn is operand values, never shapes) —
        # pre-speculation recordings lack spec_sizes and skip this
        spec_ladder = (len(cfg.get("spec_sizes") or []) *
                       len(cfg.get("pool_sizes", [])))
        if verifies and spec_ladder and len(verifies) > spec_ladder:
            failures.append(
                f"{srv}: {len(verifies)} verify compiles exceed the "
                f"pinned k ladder product {spec_ladder}")
        # distinct-program check: a repeated (pool, A, P, k) or a
        # cache_size > 1 event is a RETRACE of an existing program
        seen = set()
        for e in admits + steps + verifies:
            key = (e["site"], e.get("pool"), e.get("a_bucket"),
                   e.get("p_bucket"), e.get("k_bucket"))
            if key in seen or e.get("retrace"):
                failures.append(f"{srv}: retrace of {key}")
            seen.add(key)

    # speculative-decoding ledger (ISSUE 17): every proposed draft
    # token resolves to exactly one of accepted/rejected — re-derived
    # BOTH from the per-dispatch serve_spec events and from the
    # close-time serve_stats counters
    spec_evs = defaultdict(lambda: {"proposed": 0, "accepted": 0,
                                    "rejected": 0})
    for e in events:
        if e.get("kind") == "serve_spec":
            led = spec_evs[e.get("server", "?")]
            for f in ("proposed", "accepted", "rejected"):
                led[f] += e.get(f, 0)
    for srv, led in sorted(spec_evs.items()):
        if led["accepted"] + led["rejected"] != led["proposed"]:
            failures.append(
                f"{srv}: serve_spec events: accepted "
                f"{led['accepted']} + rejected {led['rejected']} != "
                f"proposed {led['proposed']}")
    for st in stats:
        counters = st.get("counters", {})
        prop = counters.get("draft_proposed")
        if prop is None:
            continue   # pre-speculation recording
        acc = counters.get("draft_accepted", 0)
        rej = counters.get("draft_rejected", 0)
        if acc + rej != prop:
            failures.append(
                f"{st.get('server', '?')}: serve_stats counters: "
                f"draft_accepted {acc} + draft_rejected {rej} != "
                f"draft_proposed {prop}")

    for st in stats:
        counters = st.get("counters", {})
        n_steps = st.get("steps")
        disp = counters.get("step_dispatches")
        if n_steps is not None and disp is not None and disp != n_steps:
            failures.append(
                f"{st.get('server', '?')}: {disp} step dispatches for "
                f"{n_steps} decode steps (expected exactly 1/step)")

    # pool bytes vs the configured HBM budget, across the recording:
    # the accountant timeline (device_memory events keyed by the server
    # label) plus the close-time serve_stats snapshot
    pool_peak = defaultdict(int)
    for e in events:
        if e.get("kind") == "device_memory" and \
                e.get("subsystem") == "serve.kv_pool":
            srv = e.get("key", "?")
            pool_peak[srv] = max(pool_peak[srv], e.get("bytes", 0))
    for srv, cfg in sorted(configs.items()):
        budget = cfg.get("hbm_budget")
        if budget is None:
            continue
        peak = pool_peak.get(srv, 0)
        if peak > budget:
            failures.append(
                f"{srv}: pool bytes {peak} exceed the configured "
                f"hbm_budget {budget}")
    for st in stats:
        budget = configs.get(st.get("server"), {}).get("hbm_budget")
        pb = st.get("pool_bytes")
        if budget is not None and pb is not None and pb > budget:
            failures.append(
                f"{st.get('server', '?')}: serve_stats pool_bytes "
                f"{pb} exceed the configured hbm_budget {budget}")

    # paged-pool capacity (ISSUE 16): pages in use can never exceed
    # the pool's page count — pre-paging recordings lack the fields
    # and skip the check
    for st in stats:
        total = st.get("pages_total")
        used = st.get("pages_in_use")
        if total is not None and used is not None and used > total:
            failures.append(
                f"{st.get('server', '?')}: {used} pages in use exceed "
                f"the pool capacity {total}")

    # dtype-aware page pricing (ISSUE 18): the reported pool bytes must
    # equal pages_total * the PRICED page size (codes + scales under
    # kv_dtype=int8) plus the per-slot scalar state — an int8 pool
    # billed at f32 page bytes (or vice versa) fails here.  Pre-int8
    # recordings lack page_bytes and skip the check; the retrace key
    # above is deliberately dtype-free (kv_dtype never shapes a trace
    # signature beyond the operand dtypes it already keys).
    schema = _serve_schema()
    for st in stats:
        pb = st.get("pool_bytes")
        page_bytes = st.get("page_bytes")
        total = st.get("pages_total")
        slots = st.get("num_slots")
        if None in (pb, page_bytes, total, slots) or pb == 0:
            continue   # sync mode / torn-down pool: nothing resident
        priced = total * page_bytes
        if schema is not None:
            # the slot-state layout declaration is on hand: the scalar
            # state must price to EXACTLY slots * slot_state_bytes()
            # (the same figure pool_state_bytes charges) — any gap is
            # a column added to one side of the ledger only
            expect = slots * schema.slot_state_bytes()
            ok = pb - priced == expect
        else:
            # standalone fallback: the per-slot scalars are a few
            # dozen bytes; 64 bounds them without re-pinning a layout
            # this copy of the tool can't see
            ok = 0 <= pb - priced < slots * 64
        if not ok:
            failures.append(
                f"{st.get('server', '?')}: serve_stats pool_bytes {pb} "
                f"inconsistent with {total} pages * {page_bytes} "
                f"priced page bytes (kv_dtype="
                f"{st.get('kv_dtype', 'native')})")
    if not configs and not stats:
        failures.append("no serve_config/serve_stats events in the "
                        "stream — nothing to check")
    return failures


# --------------------------------------------------------------------- #
# rendering
# --------------------------------------------------------------------- #

def render(events):
    lines = []
    comp = compile_summary(events)
    if comp:
        lines.append("compile events")
        lines.append(f"  {'site':<24}{'compiles':>9}{'retraces':>9}"
                     f"{'wall(s)':>9}{'max(s)':>8}  hlo ops")
        for r in comp:
            hlo = "-" if r["hlo_ops_min"] is None else (
                f"{r['hlo_ops_min']}"
                if r["hlo_ops_min"] == r["hlo_ops_max"]
                else f"{r['hlo_ops_min']}..{r['hlo_ops_max']}")
            lines.append(
                f"  {r['site']:<24}{r['compiles']:>9}{r['retraces']:>9}"
                f"{r['wall_s_total']:>9.2f}{r['wall_s_max']:>8.2f}  "
                f"{hlo}")
    srv = serve_summary(events)
    if srv:
        lines.append("")
        lines.append("serve requests")
        lines.append(f"  {'server':<8}{'requests':>9}{'tokens':>8}"
                     f"{'p50 ttft(ms)':>13}{'p99 ttft(ms)':>13}"
                     f"{'p50 wait(ms)':>13}{'wave':>6}  reasons")
        for r in srv:
            wave = "-" if r["mean_admit_wave"] is None \
                else f"{r['mean_admit_wave']:.1f}"
            lines.append(
                f"  {r['server']:<8}{r['requests']:>9}{r['tokens']:>8}"
                f"{_ms(r['p50_ttft_ms']):>13}{_ms(r['p99_ttft_ms']):>13}"
                f"{_ms(r['p50_queue_wait_ms']):>13}{wave:>6}  "
                f"{r['reasons']}")
    stats = [e for e in events if e.get("kind") == "serve_stats"]
    if stats:
        lines.append("")
        lines.append("serve stats (at close)")
        for st in stats:
            c = st.get("counters", {})
            lines.append(
                f"  {st.get('server', '?'):<8}steps={st.get('steps')} "
                f"occupancy={st.get('occupancy', 0):.3f} "
                f"step_dispatches={c.get('step_dispatches')} "
                f"admit_dispatches={c.get('admit_dispatches')} "
                f"pool_grows={c.get('pool_grows')} "
                f"sync_requests={c.get('sync_requests')}")
    fails = failure_summary(events)
    if fails:
        lines.append("")
        lines.append("failure causes")
        for r in fails:
            lines.append(f"  {r['kind']:<20}{r['count']:>6}")
            for where, n in r["detail"].items():
                lines.append(f"    {n:>4}x {where}")
    ckpts = checkpoint_summary(events)
    if ckpts:
        lines.append("")
        lines.append("checkpoints")
        for r in ckpts:
            lines.append(
                f"  {r['dir']}: {r['saves']} saves "
                f"({r['bytes']} bytes, last step {r['last_step']}), "
                f"{r['restores']} restores, {r['corrupt']} corrupt; "
                f"snapshot stall mean {_ms(r['snapshot_ms_mean'])} ms "
                f"max {_ms(r['snapshot_ms_max'])} ms, "
                f"write mean {_ms(r['write_ms_mean'])} ms")
    restarts = restart_summary(events)
    if restarts:
        r = restarts[0]
        lines.append("")
        lines.append("pod restarts")
        lines.append(f"  {r['restarts']} restarts, "
                     f"{r['backoff_s_total']}s total backoff, "
                     f"deepest attempt {r['max_attempt']}")
        for where, n in r["detail"].items():
            lines.append(f"    {n:>4}x {where}")
    bench = [e for e in events if e.get("kind") == "bench"]
    if bench:
        lines.append("")
        lines.append("bench rows")
        for e in bench:
            row = {k: v for k, v in e.items() if k not in ("ts", "kind")}
            lines.append("  " + json.dumps(row, sort_keys=True))
    markers = [e for e in events if e.get("kind") in ("marker", "phase")]
    if markers:
        lines.append("")
        lines.append("markers/phases: " + ", ".join(
            str(e.get("name", "?")) for e in markers))
    if not lines:
        lines.append("(no recognized telemetry events)")
    return "\n".join(lines)


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="Summarize a telemetry JSONL and re-check the "
                    "serving dispatch/retrace invariants from it.")
    ap.add_argument("path", help="JSONL file recorded via "
                                 "MXNET_TELEMETRY_JSONL or "
                                 "mx.telemetry.add_jsonl_sink; with "
                                 "--pod, alternatively a directory of "
                                 "per-rank recordings "
                                 "(tools/launch.py --telemetry-dir)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable summary instead of tables")
    ap.add_argument("--pod", action="store_true",
                    help="merge per-rank recordings and add the "
                         "per-rank rollup: which host retraced, which "
                         "host is over its HBM budget, per-rank "
                         "compile/memory/checkpoint truth")
    ap.add_argument("--hbm-budget", default=None,
                    help="per-rank device-memory budget for the --pod "
                         "over-budget verdict (bytes; K/M/G/T "
                         "suffixes accepted)")
    ap.add_argument("--check-serve", action="store_true",
                    help="verify serving invariants (ladder-bounded "
                         "compiles, zero retraces, 1 dispatch/step); "
                         "exit 1 on violation")
    args = ap.parse_args(argv)

    budget = _parse_bytes(args.hbm_budget) \
        if args.hbm_budget is not None else None
    events = load_pod(args.path) if args.pod else load(args.path)
    if args.json:
        out = {
            "events": len(events),
            "compile": compile_summary(events),
            "serve": serve_summary(events),
            "failures": failure_summary(events),
            "checkpoints": checkpoint_summary(events),
            "restarts": restart_summary(events),
            "bench": [e for e in events if e.get("kind") == "bench"],
        }
        if args.pod:
            out["pod"] = pod_summary(events, budget)
        print(json.dumps(out, indent=2, sort_keys=True))
    else:
        print(f"# {args.path}: {len(events)} events")
        if args.pod:
            print(render_pod(events, budget))
            print()
        print(render(events))

    if args.check_serve:
        failures = check_serve(events)
        if failures:
            for f in failures:
                print(f"CHECK FAILED: {f}", file=sys.stderr)
            return 1
        print("serve checks OK: ladder-bounded compiles, zero "
              "retraces, 1 dispatch/step, pool bytes within budget, "
              "draft ledger balanced")
    return 0


if __name__ == "__main__":
    sys.exit(main())
