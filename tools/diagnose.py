#!/usr/bin/env python3
"""Environment diagnosis dump (reference ``tools/diagnose.py``): python /
platform / framework / device / env-var report for bug reports."""
from __future__ import annotations

import os
import platform
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    print("----------Python Info----------")
    print("Version      :", platform.python_version())
    print("Compiler     :", platform.python_compiler())
    print("Build        :", platform.python_build())
    print("----------Platform Info----------")
    print("Platform     :", platform.platform())
    print("system       :", platform.system())
    print("machine      :", platform.machine())
    print("----------Environment----------")
    for k in sorted(os.environ):
        if k.startswith(("MXNET_", "JAX_", "XLA_", "TPU_", "DMLC_",
                         "LIBTPU")):
            print(f"{k}={os.environ[k]}")
    print("----------Framework Info----------")
    try:
        import mxnet_tpu as mx
        print("mxnet_tpu    :", mx.__version__)
        feats = mx.runtime.Features()
        enabled = [f for f in feats.keys() if feats.is_enabled(f)]
        print("features     :", " ".join(sorted(enabled)))
        from mxnet_tpu import _native
        print("native io    :", "built" if _native.available() else "absent")
    except Exception as e:
        print("mxnet_tpu import failed:", e)
    try:
        import jax
        print("jax          :", jax.__version__)
        print("devices      :", jax.devices())
        print("process      :", f"{jax.process_index()}/{jax.process_count()}")
    except Exception as e:
        print("jax device probe failed:", e)
    try:
        import numpy
        print("numpy        :", numpy.__version__)
    except ImportError:
        pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
