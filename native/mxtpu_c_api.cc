// Flat C ABI — the reference's standalone inference surface
// (src/c_api/c_predict_api.cc; SURVEY.md §3.1 "C API" row: MXPredCreate /
// MXPredSetInput / MXPredForward / MXPredGetOutputShape / MXPredGetOutput /
// MXPredFree + MXGetLastError/MXGetVersion).
//
// Design: the library embeds CPython and forwards each call to
// mxnet_tpu/capi_shim.py, which owns the handle table and numpy
// marshalling.  Any C/C++/FFI host (Scala, R, Julia bindings in the
// reference sense) can link this .so; if the host process already runs a
// Python interpreter (e.g. a ctypes caller), the existing interpreter is
// reused instead of initializing a second one.
//
// Error model mirrors the reference: every function returns 0 on success,
// -1 on failure, and MXGetLastError() returns the message (thread-local).

#include <Python.h>

#include <cstdint>
#include <cstring>
#include <map>
#include <memory>
#include <mutex>
#include <string>
#include <vector>

typedef uint32_t mx_uint;
typedef void *PredictorHandle;

static thread_local std::string g_last_error;
static std::mutex g_init_mutex;

struct MXPredState {
  long shim_handle;
  // backing store for MXPredGetOutputShape pointers (per reference
  // semantics the pointers stay valid until the next call on the handle)
  std::vector<mx_uint> shape_buf;
};

static void set_error(const std::string &msg) { g_last_error = msg; }

static void capture_py_error() {
  PyObject *type = nullptr, *value = nullptr, *tb = nullptr;
  PyErr_Fetch(&type, &value, &tb);
  std::string msg = "python error";
  if (value) {
    PyObject *s = PyObject_Str(value);
    if (s) {
      msg = PyUnicode_AsUTF8(s) ? PyUnicode_AsUTF8(s) : msg;
      Py_DECREF(s);
    }
  }
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(tb);
  set_error(msg);
}

static bool ensure_python() {
  std::lock_guard<std::mutex> lk(g_init_mutex);
  if (!Py_IsInitialized()) {
    Py_InitializeEx(0);
    // release the GIL acquired by initialization; every entry point
    // below re-acquires via PyGILState_Ensure
    PyEval_SaveThread();
  }
  return true;
}

namespace {
class Gil {
 public:
  Gil() : state_(PyGILState_Ensure()) {}
  ~Gil() { PyGILState_Release(state_); }

 private:
  PyGILState_STATE state_;
};
}  // namespace

static PyObject *shim() {
  static PyObject *mod = nullptr;  // borrowed forever once imported
  if (!mod) {
    mod = PyImport_ImportModule("mxnet_tpu.capi_shim");
  }
  return mod;
}

extern "C" {

const char *MXGetLastError() { return g_last_error.c_str(); }

int MXGetVersion(int *out) {
  if (!ensure_python()) return -1;
  Gil gil;
  PyObject *m = shim();
  if (!m) {
    capture_py_error();
    return -1;
  }
  PyObject *r = PyObject_CallMethod(m, "version", nullptr);
  if (!r) {
    capture_py_error();
    return -1;
  }
  *out = static_cast<int>(PyLong_AsLong(r));
  Py_DECREF(r);
  return 0;
}

int MXPredCreate(const char *symbol_json_file, const char *param_file,
                 int dev_type, int dev_id, mx_uint num_input_nodes,
                 const char **input_keys, const mx_uint *input_shape_indptr,
                 const mx_uint *input_shape_data, PredictorHandle *out) {
  if (!ensure_python()) return -1;
  Gil gil;
  PyObject *m = shim();
  if (!m) {
    capture_py_error();
    return -1;
  }
  PyObject *keys = PyList_New(num_input_nodes);
  PyObject *indptr = PyList_New(num_input_nodes + 1);
  for (mx_uint i = 0; i < num_input_nodes; ++i)
    PyList_SetItem(keys, i, PyUnicode_FromString(input_keys[i]));
  for (mx_uint i = 0; i <= num_input_nodes; ++i)
    PyList_SetItem(indptr, i,
                   PyLong_FromUnsignedLong(input_shape_indptr[i]));
  mx_uint n_dims = input_shape_indptr[num_input_nodes];
  PyObject *dims = PyList_New(n_dims);
  for (mx_uint i = 0; i < n_dims; ++i)
    PyList_SetItem(dims, i, PyLong_FromUnsignedLong(input_shape_data[i]));
  PyObject *r = PyObject_CallMethod(
      m, "create", "ssOOOii", symbol_json_file,
      param_file ? param_file : "", keys, indptr, dims, dev_type, dev_id);
  Py_DECREF(keys);
  Py_DECREF(indptr);
  Py_DECREF(dims);
  if (!r) {
    capture_py_error();
    return -1;
  }
  auto *st = new MXPredState();
  st->shim_handle = PyLong_AsLong(r);
  Py_DECREF(r);
  *out = st;
  return 0;
}

int MXPredSetInput(PredictorHandle handle, const char *key,
                   const float *data, mx_uint size) {
  Gil gil;
  auto *st = static_cast<MXPredState *>(handle);
  PyObject *buf = PyBytes_FromStringAndSize(
      reinterpret_cast<const char *>(data),
      static_cast<Py_ssize_t>(size) * sizeof(float));
  PyObject *r = PyObject_CallMethod(shim(), "set_input", "lsO",
                                    st->shim_handle, key, buf);
  Py_DECREF(buf);
  if (!r) {
    capture_py_error();
    return -1;
  }
  Py_DECREF(r);
  return 0;
}

int MXPredForward(PredictorHandle handle) {
  Gil gil;
  auto *st = static_cast<MXPredState *>(handle);
  PyObject *r =
      PyObject_CallMethod(shim(), "forward", "l", st->shim_handle);
  if (!r) {
    capture_py_error();
    return -1;
  }
  Py_DECREF(r);
  return 0;
}

int MXPredGetNumOutputs(PredictorHandle handle, mx_uint *out) {
  Gil gil;
  auto *st = static_cast<MXPredState *>(handle);
  PyObject *r =
      PyObject_CallMethod(shim(), "num_outputs", "l", st->shim_handle);
  if (!r) {
    capture_py_error();
    return -1;
  }
  *out = static_cast<mx_uint>(PyLong_AsUnsignedLong(r));
  Py_DECREF(r);
  return 0;
}

int MXPredGetOutputShape(PredictorHandle handle, mx_uint index,
                         mx_uint **shape_data, mx_uint *shape_ndim) {
  Gil gil;
  auto *st = static_cast<MXPredState *>(handle);
  PyObject *r = PyObject_CallMethod(shim(), "output_shape", "lI",
                                    st->shim_handle, index);
  if (!r) {
    capture_py_error();
    return -1;
  }
  Py_ssize_t n = PyTuple_Size(r);
  st->shape_buf.resize(static_cast<size_t>(n));
  for (Py_ssize_t i = 0; i < n; ++i)
    st->shape_buf[static_cast<size_t>(i)] = static_cast<mx_uint>(
        PyLong_AsUnsignedLong(PyTuple_GetItem(r, i)));
  Py_DECREF(r);
  *shape_data = st->shape_buf.data();
  *shape_ndim = static_cast<mx_uint>(n);
  return 0;
}

int MXPredGetOutput(PredictorHandle handle, mx_uint index, float *data,
                    mx_uint size) {
  Gil gil;
  auto *st = static_cast<MXPredState *>(handle);
  PyObject *r = PyObject_CallMethod(shim(), "output_bytes", "lI",
                                    st->shim_handle, index);
  if (!r) {
    capture_py_error();
    return -1;
  }
  char *buf = nullptr;
  Py_ssize_t len = 0;
  if (PyBytes_AsStringAndSize(r, &buf, &len) != 0) {
    Py_DECREF(r);
    capture_py_error();
    return -1;
  }
  if (static_cast<Py_ssize_t>(size) * 4 < len) {
    Py_DECREF(r);
    set_error("MXPredGetOutput: buffer too small");
    return -1;
  }
  std::memcpy(data, buf, static_cast<size_t>(len));
  Py_DECREF(r);
  return 0;
}

int MXPredFree(PredictorHandle handle) {
  Gil gil;
  auto *st = static_cast<MXPredState *>(handle);
  PyObject *r = PyObject_CallMethod(shim(), "free", "l", st->shim_handle);
  if (!r) {
    capture_py_error();
    delete st;
    return -1;
  }
  Py_DECREF(r);
  delete st;
  return 0;
}

}  // extern "C"

// ========================================================================
// Training ABI subset (reference src/c_api/c_api.cc: MXNDArray* /
// MXSymbol* / MXExecutor*, SURVEY.md §3.1 "C API" row; VERDICT r3
// item 5).  float32; enough for a C host to run a full train loop:
// create arrays, copy in/out, bind, forward, backward, read grads.
// ========================================================================

typedef void *NDArrayHandle;
typedef void *SymbolHandle;
typedef void *ExecutorHandle;

struct MXNDState {
  long shim_handle;
  std::vector<mx_uint> shape_buf;  // MXNDArrayGetShape backing store
};

struct MXSymState {
  long shim_handle;
  // MXSymbolInferShape backing stores (valid until next call, per
  // reference semantics)
  std::vector<std::vector<mx_uint>> shapes;
  std::vector<mx_uint> ndims[3];
  std::vector<const mx_uint *> datas[3];
};

struct MXExecState {
  long shim_handle;
};

// call a shim function returning a long handle; -1 on python error
static long call_long(PyObject *r) {
  if (!r) {
    capture_py_error();
    return -1;
  }
  long v = PyLong_AsLong(r);
  Py_DECREF(r);
  return v;
}

extern "C" {

int MXNDArrayCreate(const mx_uint *shape, mx_uint ndim, int dev_type,
                    int dev_id, int delay_alloc, NDArrayHandle *out) {
  (void)dev_type; (void)dev_id; (void)delay_alloc;
  if (!ensure_python()) return -1;
  Gil gil;
  PyObject *shp = PyList_New(ndim);
  for (mx_uint i = 0; i < ndim; ++i)
    PyList_SetItem(shp, i, PyLong_FromUnsignedLong(shape[i]));
  long h = call_long(PyObject_CallMethod(shim(), "nd_create", "O", shp));
  Py_DECREF(shp);
  if (h < 0) return -1;
  auto *st = new MXNDState();
  st->shim_handle = h;
  *out = st;
  return 0;
}

int MXNDArrayFree(NDArrayHandle handle) {
  Gil gil;
  auto *st = static_cast<MXNDState *>(handle);
  PyObject *r =
      PyObject_CallMethod(shim(), "nd_free", "l", st->shim_handle);
  Py_XDECREF(r);
  delete st;
  return r ? 0 : (capture_py_error(), -1);
}

// `size` counts ELEMENTS (reference semantics), not bytes
int MXNDArraySyncCopyFromCPU(NDArrayHandle handle, const void *data,
                             size_t size) {
  Gil gil;
  auto *st = static_cast<MXNDState *>(handle);
  PyObject *buf = PyBytes_FromStringAndSize(
      static_cast<const char *>(data),
      static_cast<Py_ssize_t>(size * sizeof(float)));
  PyObject *r = PyObject_CallMethod(shim(), "nd_sync_copy_from", "lO",
                                    st->shim_handle, buf);
  Py_DECREF(buf);
  if (!r) {
    capture_py_error();
    return -1;
  }
  Py_DECREF(r);
  return 0;
}

int MXNDArraySyncCopyToCPU(NDArrayHandle handle, void *data, size_t size) {
  Gil gil;
  auto *st = static_cast<MXNDState *>(handle);
  PyObject *r = PyObject_CallMethod(shim(), "nd_sync_copy_to", "l",
                                    st->shim_handle);
  if (!r) {
    capture_py_error();
    return -1;
  }
  char *buf = nullptr;
  Py_ssize_t len = 0;
  if (PyBytes_AsStringAndSize(r, &buf, &len) != 0) {
    Py_DECREF(r);
    capture_py_error();
    return -1;
  }
  if (static_cast<Py_ssize_t>(size * sizeof(float)) != len) {
    Py_DECREF(r);
    set_error("MXNDArraySyncCopyToCPU: size mismatch (" +
              std::to_string(size) + " elements requested, array has " +
              std::to_string(len / sizeof(float)) + ")");
    return -1;
  }
  std::memcpy(data, buf, static_cast<size_t>(len));
  Py_DECREF(r);
  return 0;
}

int MXNDArrayGetShape(NDArrayHandle handle, mx_uint *out_dim,
                      const mx_uint **out_pdata) {
  Gil gil;
  auto *st = static_cast<MXNDState *>(handle);
  PyObject *r = PyObject_CallMethod(shim(), "nd_get_shape", "l",
                                    st->shim_handle);
  if (!r) {
    capture_py_error();
    return -1;
  }
  Py_ssize_t n = PyTuple_Size(r);
  st->shape_buf.resize(static_cast<size_t>(n));
  for (Py_ssize_t i = 0; i < n; ++i)
    st->shape_buf[static_cast<size_t>(i)] = static_cast<mx_uint>(
        PyLong_AsUnsignedLong(PyTuple_GetItem(r, i)));
  Py_DECREF(r);
  *out_dim = static_cast<mx_uint>(n);
  *out_pdata = st->shape_buf.data();
  return 0;
}

int MXNDArrayGetDType(NDArrayHandle handle, int *out_dtype) {
  Gil gil;
  auto *st = static_cast<MXNDState *>(handle);
  long v = call_long(PyObject_CallMethod(shim(), "nd_get_dtype", "l",
                                         st->shim_handle));
  if (v < 0) return -1;
  *out_dtype = static_cast<int>(v);
  return 0;
}

int MXNDArraySave(const char *fname, mx_uint num_args,
                  NDArrayHandle *args, const char **keys) {
  Gil gil;
  PyObject *hs = PyList_New(num_args);
  PyObject *ks = keys ? PyList_New(num_args) : Py_None;
  for (mx_uint i = 0; i < num_args; ++i) {
    PyList_SetItem(hs, i, PyLong_FromLong(
        static_cast<MXNDState *>(args[i])->shim_handle));
    if (keys)
      PyList_SetItem(ks, i, PyUnicode_FromString(keys[i]));
  }
  PyObject *r = PyObject_CallMethod(shim(), "nd_save", "sOO", fname, hs,
                                    ks);
  Py_DECREF(hs);
  if (keys) Py_DECREF(ks);
  if (!r) {
    capture_py_error();
    return -1;
  }
  Py_DECREF(r);
  return 0;
}

int MXNDArrayLoad(const char *fname, mx_uint *out_size,
                  NDArrayHandle **out_arr, mx_uint *out_name_size,
                  const char ***out_names) {
  if (!ensure_python()) return -1;
  Gil gil;
  PyObject *r = PyObject_CallMethod(shim(), "nd_load", "s", fname);
  if (!r) {
    capture_py_error();
    return -1;
  }
  PyObject *hs = PyTuple_GetItem(r, 0);
  PyObject *ns = PyTuple_GetItem(r, 1);
  static thread_local std::vector<NDArrayHandle> arr_store;
  static thread_local std::vector<std::string> name_store;
  static thread_local std::vector<const char *> name_ptrs;
  arr_store.clear();
  name_store.clear();
  name_ptrs.clear();
  for (Py_ssize_t i = 0; i < PyTuple_Size(hs); ++i) {
    auto *nd = new MXNDState();
    nd->shim_handle = PyLong_AsLong(PyTuple_GetItem(hs, i));
    arr_store.push_back(nd);
  }
  for (Py_ssize_t i = 0; i < PyTuple_Size(ns); ++i)
    name_store.emplace_back(PyUnicode_AsUTF8(PyTuple_GetItem(ns, i)));
  for (auto &s : name_store) name_ptrs.push_back(s.c_str());
  Py_DECREF(r);
  *out_size = static_cast<mx_uint>(arr_store.size());
  *out_arr = arr_store.data();
  *out_name_size = static_cast<mx_uint>(name_store.size());
  *out_names = name_ptrs.data();
  return 0;
}

int MXSymbolSaveToFile(SymbolHandle handle, const char *fname) {
  Gil gil;
  auto *st = static_cast<MXSymState *>(handle);
  PyObject *r = PyObject_CallMethod(shim(), "sym_save_to_file", "ls",
                                    st->shim_handle, fname);
  if (!r) {
    capture_py_error();
    return -1;
  }
  Py_DECREF(r);
  return 0;
}

int MXSymbolCreateFromFile(const char *fname, SymbolHandle *out) {
  if (!ensure_python()) return -1;
  Gil gil;
  long h = call_long(
      PyObject_CallMethod(shim(), "sym_create_from_file", "s", fname));
  if (h < 0) return -1;
  auto *st = new MXSymState();
  st->shim_handle = h;
  *out = st;
  return 0;
}

int MXSymbolFree(SymbolHandle handle) {
  Gil gil;
  auto *st = static_cast<MXSymState *>(handle);
  PyObject *r = PyObject_CallMethod(shim(), "free", "l", st->shim_handle);
  Py_XDECREF(r);
  delete st;
  return r ? 0 : (capture_py_error(), -1);
}

// list_arguments via a CSV into a caller buffer would diverge from the
// reference; instead expose the count + per-index name (both shim-side
// tuples are cheap) so hosts can build arg tables.
int MXSymbolListArguments(SymbolHandle handle, mx_uint *out_size,
                          const char ***out_str_array) {
  Gil gil;
  auto *st = static_cast<MXSymState *>(handle);
  PyObject *r = PyObject_CallMethod(shim(), "sym_list_arguments", "l",
                                    st->shim_handle);
  if (!r) {
    capture_py_error();
    return -1;
  }
  static thread_local std::vector<std::string> name_store;
  static thread_local std::vector<const char *> ptr_store;
  Py_ssize_t n = PyTuple_Size(r);
  name_store.clear();
  ptr_store.clear();
  for (Py_ssize_t i = 0; i < n; ++i)
    name_store.emplace_back(PyUnicode_AsUTF8(PyTuple_GetItem(r, i)));
  for (auto &s : name_store) ptr_store.push_back(s.c_str());
  Py_DECREF(r);
  *out_size = static_cast<mx_uint>(n);
  *out_str_array = ptr_store.data();
  return 0;
}

int MXSymbolInferShape(
    SymbolHandle handle, mx_uint num_args, const char **keys,
    const mx_uint *arg_ind_ptr, const mx_uint *arg_shape_data,
    mx_uint *in_shape_size, const mx_uint **in_shape_ndim,
    const mx_uint ***in_shape_data, mx_uint *out_shape_size,
    const mx_uint **out_shape_ndim, const mx_uint ***out_shape_data,
    mx_uint *aux_shape_size, const mx_uint **aux_shape_ndim,
    const mx_uint ***aux_shape_data, int *complete) {
  Gil gil;
  auto *st = static_cast<MXSymState *>(handle);
  PyObject *k = PyList_New(num_args);
  PyObject *ip = PyList_New(num_args + 1);
  for (mx_uint i = 0; i < num_args; ++i)
    PyList_SetItem(k, i, PyUnicode_FromString(keys[i]));
  for (mx_uint i = 0; i <= num_args; ++i)
    PyList_SetItem(ip, i, PyLong_FromUnsignedLong(arg_ind_ptr[i]));
  mx_uint nd = arg_ind_ptr[num_args];
  PyObject *sd = PyList_New(nd);
  for (mx_uint i = 0; i < nd; ++i)
    PyList_SetItem(sd, i, PyLong_FromUnsignedLong(arg_shape_data[i]));
  PyObject *r = PyObject_CallMethod(shim(), "sym_infer_shape", "lOOO",
                                    st->shim_handle, k, ip, sd);
  Py_DECREF(k);
  Py_DECREF(ip);
  Py_DECREF(sd);
  if (!r) {
    capture_py_error();
    return -1;
  }
  st->shapes.clear();
  bool all_known = true;  // hosts branch on *complete (reference ABI)
  mx_uint *sizes[3] = {in_shape_size, out_shape_size, aux_shape_size};
  const mx_uint **ndims_out[3] = {in_shape_ndim, out_shape_ndim,
                                  aux_shape_ndim};
  const mx_uint ***datas_out[3] = {in_shape_data, out_shape_data,
                                   aux_shape_data};
  for (int g = 0; g < 3; ++g) {
    PyObject *grp = PyTuple_GetItem(r, g);
    Py_ssize_t n = PyTuple_Size(grp);
    st->ndims[g].clear();
    st->datas[g].clear();
    for (Py_ssize_t i = 0; i < n; ++i) {
      PyObject *shp = PyTuple_GetItem(grp, i);
      Py_ssize_t m = PyTuple_Size(shp);
      st->shapes.emplace_back();
      auto &vec = st->shapes.back();
      for (Py_ssize_t j = 0; j < m; ++j) {
        mx_uint dim = static_cast<mx_uint>(
            PyLong_AsUnsignedLong(PyTuple_GetItem(shp, j)));
        if (dim == 0) all_known = false;  // 0 = unknown after partial infer
        vec.push_back(dim);
      }
      st->ndims[g].push_back(static_cast<mx_uint>(m));
    }
  }
  // second pass for data pointers: st->shapes no longer reallocates
  size_t idx = 0;
  for (int g = 0; g < 3; ++g) {
    for (size_t i = 0; i < st->ndims[g].size(); ++i)
      st->datas[g].push_back(st->shapes[idx++].data());
    *sizes[g] = static_cast<mx_uint>(st->ndims[g].size());
    *ndims_out[g] = st->ndims[g].data();
    *datas_out[g] = st->datas[g].data();
  }
  Py_DECREF(r);
  *complete = all_known ? 1 : 0;
  return 0;
}

int MXExecutorBind(SymbolHandle symbol_handle, int dev_type, int dev_id,
                   mx_uint len, NDArrayHandle *in_args,
                   NDArrayHandle *arg_grad_store, mx_uint *grad_req_type,
                   mx_uint aux_states_len, NDArrayHandle *aux_states,
                   ExecutorHandle *out) {
  (void)dev_type; (void)dev_id; (void)aux_states_len; (void)aux_states;
  Gil gil;
  auto *sym = static_cast<MXSymState *>(symbol_handle);
  PyObject *args = PyList_New(len);
  PyObject *grads = PyList_New(len);
  PyObject *reqs = PyList_New(len);
  for (mx_uint i = 0; i < len; ++i) {
    PyList_SetItem(args, i, PyLong_FromLong(
        static_cast<MXNDState *>(in_args[i])->shim_handle));
    PyList_SetItem(grads, i, PyLong_FromLong(
        arg_grad_store && arg_grad_store[i]
            ? static_cast<MXNDState *>(arg_grad_store[i])->shim_handle
            : 0));
    PyList_SetItem(reqs, i, PyLong_FromUnsignedLong(
        grad_req_type ? grad_req_type[i] : 0));
  }
  long h = call_long(PyObject_CallMethod(
      shim(), "executor_bind", "lOOO", sym->shim_handle, args, grads,
      reqs));
  Py_DECREF(args);
  Py_DECREF(grads);
  Py_DECREF(reqs);
  if (h < 0) return -1;
  auto *st = new MXExecState();
  st->shim_handle = h;
  *out = st;
  return 0;
}

int MXExecutorForward(ExecutorHandle handle, int is_train) {
  Gil gil;
  auto *st = static_cast<MXExecState *>(handle);
  PyObject *r = PyObject_CallMethod(shim(), "executor_forward", "li",
                                    st->shim_handle, is_train);
  if (!r) {
    capture_py_error();
    return -1;
  }
  Py_DECREF(r);
  return 0;
}

int MXExecutorBackward(ExecutorHandle handle, mx_uint len,
                       NDArrayHandle *head_grads) {
  (void)len; (void)head_grads;  // mean-loss heads: default ones
  Gil gil;
  auto *st = static_cast<MXExecState *>(handle);
  PyObject *r = PyObject_CallMethod(shim(), "executor_backward", "l",
                                    st->shim_handle);
  if (!r) {
    capture_py_error();
    return -1;
  }
  Py_DECREF(r);
  return 0;
}

int MXExecutorOutputs(ExecutorHandle handle, mx_uint *out_size,
                      NDArrayHandle **out) {
  Gil gil;
  auto *st = static_cast<MXExecState *>(handle);
  long n = call_long(PyObject_CallMethod(shim(), "executor_num_outputs",
                                         "l", st->shim_handle));
  if (n < 0) return -1;
  static thread_local std::vector<NDArrayHandle> out_store;
  out_store.clear();
  for (long i = 0; i < n; ++i) {
    long h = call_long(PyObject_CallMethod(
        shim(), "executor_output", "ll", st->shim_handle, i));
    if (h < 0) {
      // release the handles already wrapped: the caller never sees them
      for (NDArrayHandle created : out_store) {
        auto *nd = static_cast<MXNDState *>(created);
        PyObject *fr = PyObject_CallMethod(shim(), "free", "l",
                                           nd->shim_handle);
        Py_XDECREF(fr);
        delete nd;
      }
      out_store.clear();
      return -1;
    }
    auto *nd = new MXNDState();
    nd->shim_handle = h;
    out_store.push_back(nd);
  }
  *out_size = static_cast<mx_uint>(n);
  *out = out_store.data();
  return 0;
}

int MXExecutorFree(ExecutorHandle handle) {
  Gil gil;
  auto *st = static_cast<MXExecState *>(handle);
  PyObject *r = PyObject_CallMethod(shim(), "free", "l", st->shim_handle);
  Py_XDECREF(r);
  delete st;
  return r ? 0 : (capture_py_error(), -1);
}

}  // extern "C"

// ========================================================================
// Imperative op invocation (reference src/c_api/c_api_ndarray.cc:
// MXImperativeInvoke[Ex] + op discovery, SURVEY.md §3.1 C API row and
// call stack §4.1 — the per-op fast path every language binding sits
// on).  Op handles are interned name strings; attrs cross as strings
// and parse shim-side like dmlc::Parameter.
// ========================================================================

typedef void *OpHandle;
typedef void *AtomicSymbolCreator;

extern "C" {

int MXListAllOpNames(mx_uint *out_size, const char ***out_array) {
  if (!ensure_python()) return -1;
  Gil gil;
  // valid until the next call on THIS thread (the file-wide ret-store
  // convention)
  static thread_local std::vector<std::string> name_store;
  static thread_local std::vector<const char *> ptr_store;
  PyObject *r = PyObject_CallMethod(shim(), "op_list_names", nullptr);
  if (!r) {
    capture_py_error();
    return -1;
  }
  Py_ssize_t n = PyTuple_Size(r);
  name_store.clear();
  ptr_store.clear();
  for (Py_ssize_t i = 0; i < n; ++i) {
    const char *s = PyUnicode_AsUTF8(PyTuple_GetItem(r, i));
    name_store.emplace_back(s ? s : "");
  }
  Py_DECREF(r);
  for (auto &s : name_store) ptr_store.push_back(s.c_str());
  *out_size = static_cast<mx_uint>(n);
  *out_array = ptr_store.data();
  return 0;
}

// Name -> op handle (nnvm ABI anchor NNGetOpHandle).  Validates against
// the registry so hosts fail at lookup, not mid-invoke.
int NNGetOpHandle(const char *name, OpHandle *out) {
  if (!ensure_python()) return -1;
  Gil gil;
  PyObject *r = PyObject_CallMethod(shim(), "op_exists", "s", name);
  long ok = call_long(r);
  if (ok < 0) return -1;
  if (!ok) {
    set_error(std::string("unknown operator: ") + name);
    return -1;
  }
  static std::mutex mu;
  static std::map<std::string, std::unique_ptr<std::string>> interned;
  std::lock_guard<std::mutex> lk(mu);
  auto it = interned.find(name);
  if (it == interned.end())
    it = interned.emplace(name,
                          std::unique_ptr<std::string>(
                              new std::string(name))).first;
  *out = const_cast<char *>(it->second->c_str());
  return 0;
}

// creator = an OpHandle from NNGetOpHandle.  On entry *num_outputs may
// carry caller-supplied output handles (in-place update semantics, e.g.
// sgd_update with out=weight); 0 means the op allocates.  Allocated
// output handles are owned by the caller (MXNDArrayFree); the *outputs
// pointer array itself stays valid until the next invoke on this thread
// (reference thread-local ret-store semantics).
int MXImperativeInvoke(AtomicSymbolCreator creator, int num_inputs,
                       NDArrayHandle *inputs, int *num_outputs,
                       NDArrayHandle **outputs, int num_params,
                       const char **param_keys, const char **param_vals) {
  if (!ensure_python()) return -1;
  Gil gil;
  const char *name = static_cast<const char *>(creator);
  PyObject *ins = PyList_New(num_inputs);
  for (int i = 0; i < num_inputs; ++i)
    PyList_SetItem(ins, i, PyLong_FromLong(
        static_cast<MXNDState *>(inputs[i])->shim_handle));
  int n_out_in = *num_outputs;
  PyObject *outs_in = PyList_New(n_out_in);
  for (int i = 0; i < n_out_in; ++i)
    PyList_SetItem(outs_in, i, PyLong_FromLong(
        static_cast<MXNDState *>((*outputs)[i])->shim_handle));
  PyObject *keys = PyList_New(num_params);
  PyObject *vals = PyList_New(num_params);
  for (int i = 0; i < num_params; ++i) {
    PyList_SetItem(keys, i, PyUnicode_FromString(param_keys[i]));
    PyList_SetItem(vals, i, PyUnicode_FromString(param_vals[i]));
  }
  PyObject *r = PyObject_CallMethod(shim(), "imperative_invoke", "sOOOO",
                                    name, ins, outs_in, keys, vals);
  Py_DECREF(ins);
  Py_DECREF(outs_in);
  Py_DECREF(keys);
  Py_DECREF(vals);
  if (!r) {
    capture_py_error();
    return -1;
  }
  if (n_out_in > 0) {
    // caller-supplied handles were rebound in place; nothing to return
    *num_outputs = n_out_in;
    Py_DECREF(r);
    return 0;
  }
  Py_ssize_t n = PyTuple_Size(r);
  static thread_local std::vector<NDArrayHandle> out_store;
  out_store.clear();
  for (Py_ssize_t i = 0; i < n; ++i) {
    auto *nd = new MXNDState();
    nd->shim_handle = PyLong_AsLong(PyTuple_GetItem(r, i));
    out_store.push_back(nd);
  }
  Py_DECREF(r);
  *num_outputs = static_cast<int>(n);
  *outputs = out_store.data();
  return 0;
}

// Ex variant (reference MXImperativeInvokeEx): adds output storage-type
// reporting — dense-only here (kDefaultStorage = 0), matching the
// registry's dense ndarray handles.
int MXImperativeInvokeEx(AtomicSymbolCreator creator, int num_inputs,
                         NDArrayHandle *inputs, int *num_outputs,
                         NDArrayHandle **outputs, int num_params,
                         const char **param_keys, const char **param_vals,
                         const int **out_stypes) {
  int rc = MXImperativeInvoke(creator, num_inputs, inputs, num_outputs,
                              outputs, num_params, param_keys, param_vals);
  if (rc != 0) return rc;
  static thread_local std::vector<int> stype_store;
  stype_store.assign(static_cast<size_t>(*num_outputs), 0);
  *out_stypes = stype_store.data();
  return 0;
}

}  // extern "C"
