// Flat C ABI — the reference's standalone inference surface
// (src/c_api/c_predict_api.cc; SURVEY.md §3.1 "C API" row: MXPredCreate /
// MXPredSetInput / MXPredForward / MXPredGetOutputShape / MXPredGetOutput /
// MXPredFree + MXGetLastError/MXGetVersion).
//
// Design: the library embeds CPython and forwards each call to
// mxnet_tpu/capi_shim.py, which owns the handle table and numpy
// marshalling.  Any C/C++/FFI host (Scala, R, Julia bindings in the
// reference sense) can link this .so; if the host process already runs a
// Python interpreter (e.g. a ctypes caller), the existing interpreter is
// reused instead of initializing a second one.
//
// Error model mirrors the reference: every function returns 0 on success,
// -1 on failure, and MXGetLastError() returns the message (thread-local).

#include <Python.h>

#include <cstdint>
#include <cstring>
#include <mutex>
#include <string>
#include <vector>

typedef uint32_t mx_uint;
typedef void *PredictorHandle;

static thread_local std::string g_last_error;
static std::mutex g_init_mutex;

struct MXPredState {
  long shim_handle;
  // backing store for MXPredGetOutputShape pointers (per reference
  // semantics the pointers stay valid until the next call on the handle)
  std::vector<mx_uint> shape_buf;
};

static void set_error(const std::string &msg) { g_last_error = msg; }

static void capture_py_error() {
  PyObject *type = nullptr, *value = nullptr, *tb = nullptr;
  PyErr_Fetch(&type, &value, &tb);
  std::string msg = "python error";
  if (value) {
    PyObject *s = PyObject_Str(value);
    if (s) {
      msg = PyUnicode_AsUTF8(s) ? PyUnicode_AsUTF8(s) : msg;
      Py_DECREF(s);
    }
  }
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(tb);
  set_error(msg);
}

static bool ensure_python() {
  std::lock_guard<std::mutex> lk(g_init_mutex);
  if (!Py_IsInitialized()) {
    Py_InitializeEx(0);
    // release the GIL acquired by initialization; every entry point
    // below re-acquires via PyGILState_Ensure
    PyEval_SaveThread();
  }
  return true;
}

namespace {
class Gil {
 public:
  Gil() : state_(PyGILState_Ensure()) {}
  ~Gil() { PyGILState_Release(state_); }

 private:
  PyGILState_STATE state_;
};
}  // namespace

static PyObject *shim() {
  static PyObject *mod = nullptr;  // borrowed forever once imported
  if (!mod) {
    mod = PyImport_ImportModule("mxnet_tpu.capi_shim");
  }
  return mod;
}

extern "C" {

const char *MXGetLastError() { return g_last_error.c_str(); }

int MXGetVersion(int *out) {
  if (!ensure_python()) return -1;
  Gil gil;
  PyObject *m = shim();
  if (!m) {
    capture_py_error();
    return -1;
  }
  PyObject *r = PyObject_CallMethod(m, "version", nullptr);
  if (!r) {
    capture_py_error();
    return -1;
  }
  *out = static_cast<int>(PyLong_AsLong(r));
  Py_DECREF(r);
  return 0;
}

int MXPredCreate(const char *symbol_json_file, const char *param_file,
                 int dev_type, int dev_id, mx_uint num_input_nodes,
                 const char **input_keys, const mx_uint *input_shape_indptr,
                 const mx_uint *input_shape_data, PredictorHandle *out) {
  if (!ensure_python()) return -1;
  Gil gil;
  PyObject *m = shim();
  if (!m) {
    capture_py_error();
    return -1;
  }
  PyObject *keys = PyList_New(num_input_nodes);
  PyObject *indptr = PyList_New(num_input_nodes + 1);
  for (mx_uint i = 0; i < num_input_nodes; ++i)
    PyList_SetItem(keys, i, PyUnicode_FromString(input_keys[i]));
  for (mx_uint i = 0; i <= num_input_nodes; ++i)
    PyList_SetItem(indptr, i,
                   PyLong_FromUnsignedLong(input_shape_indptr[i]));
  mx_uint n_dims = input_shape_indptr[num_input_nodes];
  PyObject *dims = PyList_New(n_dims);
  for (mx_uint i = 0; i < n_dims; ++i)
    PyList_SetItem(dims, i, PyLong_FromUnsignedLong(input_shape_data[i]));
  PyObject *r = PyObject_CallMethod(
      m, "create", "ssOOOii", symbol_json_file,
      param_file ? param_file : "", keys, indptr, dims, dev_type, dev_id);
  Py_DECREF(keys);
  Py_DECREF(indptr);
  Py_DECREF(dims);
  if (!r) {
    capture_py_error();
    return -1;
  }
  auto *st = new MXPredState();
  st->shim_handle = PyLong_AsLong(r);
  Py_DECREF(r);
  *out = st;
  return 0;
}

int MXPredSetInput(PredictorHandle handle, const char *key,
                   const float *data, mx_uint size) {
  Gil gil;
  auto *st = static_cast<MXPredState *>(handle);
  PyObject *buf = PyBytes_FromStringAndSize(
      reinterpret_cast<const char *>(data),
      static_cast<Py_ssize_t>(size) * sizeof(float));
  PyObject *r = PyObject_CallMethod(shim(), "set_input", "lsO",
                                    st->shim_handle, key, buf);
  Py_DECREF(buf);
  if (!r) {
    capture_py_error();
    return -1;
  }
  Py_DECREF(r);
  return 0;
}

int MXPredForward(PredictorHandle handle) {
  Gil gil;
  auto *st = static_cast<MXPredState *>(handle);
  PyObject *r =
      PyObject_CallMethod(shim(), "forward", "l", st->shim_handle);
  if (!r) {
    capture_py_error();
    return -1;
  }
  Py_DECREF(r);
  return 0;
}

int MXPredGetNumOutputs(PredictorHandle handle, mx_uint *out) {
  Gil gil;
  auto *st = static_cast<MXPredState *>(handle);
  PyObject *r =
      PyObject_CallMethod(shim(), "num_outputs", "l", st->shim_handle);
  if (!r) {
    capture_py_error();
    return -1;
  }
  *out = static_cast<mx_uint>(PyLong_AsUnsignedLong(r));
  Py_DECREF(r);
  return 0;
}

int MXPredGetOutputShape(PredictorHandle handle, mx_uint index,
                         mx_uint **shape_data, mx_uint *shape_ndim) {
  Gil gil;
  auto *st = static_cast<MXPredState *>(handle);
  PyObject *r = PyObject_CallMethod(shim(), "output_shape", "lI",
                                    st->shim_handle, index);
  if (!r) {
    capture_py_error();
    return -1;
  }
  Py_ssize_t n = PyTuple_Size(r);
  st->shape_buf.resize(static_cast<size_t>(n));
  for (Py_ssize_t i = 0; i < n; ++i)
    st->shape_buf[static_cast<size_t>(i)] = static_cast<mx_uint>(
        PyLong_AsUnsignedLong(PyTuple_GetItem(r, i)));
  Py_DECREF(r);
  *shape_data = st->shape_buf.data();
  *shape_ndim = static_cast<mx_uint>(n);
  return 0;
}

int MXPredGetOutput(PredictorHandle handle, mx_uint index, float *data,
                    mx_uint size) {
  Gil gil;
  auto *st = static_cast<MXPredState *>(handle);
  PyObject *r = PyObject_CallMethod(shim(), "output_bytes", "lI",
                                    st->shim_handle, index);
  if (!r) {
    capture_py_error();
    return -1;
  }
  char *buf = nullptr;
  Py_ssize_t len = 0;
  if (PyBytes_AsStringAndSize(r, &buf, &len) != 0) {
    Py_DECREF(r);
    capture_py_error();
    return -1;
  }
  if (static_cast<Py_ssize_t>(size) * 4 < len) {
    Py_DECREF(r);
    set_error("MXPredGetOutput: buffer too small");
    return -1;
  }
  std::memcpy(data, buf, static_cast<size_t>(len));
  Py_DECREF(r);
  return 0;
}

int MXPredFree(PredictorHandle handle) {
  Gil gil;
  auto *st = static_cast<MXPredState *>(handle);
  PyObject *r = PyObject_CallMethod(shim(), "free", "l", st->shim_handle);
  if (!r) {
    capture_py_error();
    delete st;
    return -1;
  }
  Py_DECREF(r);
  delete st;
  return 0;
}

}  // extern "C"
