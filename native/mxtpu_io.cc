// mxtpu_io — native IO runtime for mxnet_tpu.
//
// TPU-native analog of the reference's C++ data path (SURVEY.md §3.1
// "C++ data pipeline" row: ImageRecordIOParser2 / PrefetcherIter backed by
// dmlc recordio + OMP decode pool; §4.5 call stack).  The device compute
// path is JAX/XLA; this library owns the host side: record parsing, JPEG
// decode, and a threaded prefetch queue feeding pinned host buffers.
//
// Flat C ABI, consumed from Python via ctypes (no pybind11 in this image).
//
// RecordIO format (dmlc): uint32 magic 0xced7230a | uint32 lrec
// (cflag:3 | len:29) | payload | pad to 4B.

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <atomic>
#include <condition_variable>
#include <deque>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#include <fcntl.h>
#include <sys/stat.h>
#include <unistd.h>

#include <csetjmp>
#include <jpeglib.h>

namespace {

constexpr uint32_t kMagic = 0xced7230a;
constexpr uint32_t kLenMask = (1u << 29) - 1;

thread_local std::string g_error;

void set_error(const std::string& msg) { g_error = msg; }

// ------------------------------------------------------------------ //
// Reader: offset-indexed random access over a .rec file
// ------------------------------------------------------------------ //
struct Reader {
  int fd = -1;
  int64_t file_size = 0;
  std::vector<int64_t> offsets;  // byte offset of each record header

  ~Reader() {
    if (fd >= 0) close(fd);
  }

  bool open(const char* path, const char* idx_path) {
    fd = ::open(path, O_RDONLY);
    if (fd < 0) {
      set_error(std::string("cannot open ") + path);
      return false;
    }
    struct stat st;
    fstat(fd, &st);
    file_size = st.st_size;
    if (idx_path && idx_path[0]) {
      FILE* f = fopen(idx_path, "r");
      if (f) {
        char line[256];
        while (fgets(line, sizeof(line), f)) {
          long long key, off;
          if (sscanf(line, "%lld\t%lld", &key, &off) == 2)
            offsets.push_back(off);
        }
        fclose(f);
        if (!offsets.empty()) return true;
      }
    }
    return scan();
  }

  // build the offset table by walking record headers
  bool scan() {
    offsets.clear();
    int64_t pos = 0;
    uint32_t head[2];
    while (pos + 8 <= file_size) {
      if (pread(fd, head, 8, pos) != 8) break;
      if (head[0] != kMagic) {
        set_error("bad record magic during scan");
        return false;
      }
      uint32_t len = head[1] & kLenMask;
      uint32_t cflag = head[1] >> 29;
      if (cflag == 0 || cflag == 1) offsets.push_back(pos);
      pos += 8 + ((len + 3) & ~3u);
    }
    return true;
  }

  // read record i (reassembling multi-part); returns malloc'd buffer
  uint8_t* read(int64_t i, int64_t* out_len) {
    if (i < 0 || i >= (int64_t)offsets.size()) {
      set_error("record index out of range");
      return nullptr;
    }
    int64_t pos = offsets[i];
    std::vector<uint8_t> acc;
    while (true) {
      uint32_t head[2];
      if (pread(fd, head, 8, pos) != 8 || head[0] != kMagic) {
        set_error("truncated/corrupt record");
        return nullptr;
      }
      uint32_t len = head[1] & kLenMask;
      uint32_t cflag = head[1] >> 29;
      size_t old = acc.size();
      acc.resize(old + len);
      if (pread(fd, acc.data() + old, len, pos + 8) != (ssize_t)len) {
        set_error("short read");
        return nullptr;
      }
      pos += 8 + ((len + 3) & ~3u);
      if (cflag == 0 || cflag == 3) break;
    }
    uint8_t* out = (uint8_t*)malloc(acc.size());
    memcpy(out, acc.data(), acc.size());
    *out_len = acc.size();
    return out;
  }
};

// ------------------------------------------------------------------ //
// Writer
// ------------------------------------------------------------------ //
struct Writer {
  FILE* f = nullptr;
  FILE* fidx = nullptr;
  int64_t key = 0;

  ~Writer() {
    if (f) fclose(f);
    if (fidx) fclose(fidx);
  }

  bool open(const char* path, const char* idx_path) {
    f = fopen(path, "wb");
    if (!f) {
      set_error(std::string("cannot open ") + path);
      return false;
    }
    if (idx_path && idx_path[0]) fidx = fopen(idx_path, "w");
    return true;
  }

  bool write(const uint8_t* buf, int64_t len) {
    if ((uint64_t)len > kLenMask) {
      set_error("record exceeds 2^29-1 bytes");
      return false;
    }
    int64_t pos = ftell(f);
    uint32_t head[2] = {kMagic, (uint32_t)len & kLenMask};
    if (fwrite(head, 1, 8, f) != 8) return false;
    if (len && fwrite(buf, 1, len, f) != (size_t)len) return false;
    static const uint8_t zeros[4] = {0, 0, 0, 0};
    size_t pad = (-(size_t)len) & 3;
    if (pad) fwrite(zeros, 1, pad, f);
    if (fidx) fprintf(fidx, "%lld\t%lld\n", (long long)key++, (long long)pos);
    return true;
  }
};

// ------------------------------------------------------------------ //
// JPEG decode (libjpeg) with error-trap (no exit() on corrupt input)
// ------------------------------------------------------------------ //
struct JpegErr {
  jpeg_error_mgr mgr;
  jmp_buf jb;
};

void jpeg_err_exit(j_common_ptr cinfo) {
  JpegErr* err = (JpegErr*)cinfo->err;
  longjmp(err->jb, 1);
}

// decode to RGB (or gray) uint8 HWC; returns malloc'd buffer
uint8_t* decode_jpeg(const uint8_t* buf, int64_t len, int want_color,
                     int* w, int* h, int* c) {
  jpeg_decompress_struct cinfo;
  JpegErr jerr;
  cinfo.err = jpeg_std_error(&jerr.mgr);
  jerr.mgr.error_exit = jpeg_err_exit;
  // volatile: modified between setjmp and longjmp, read in the error path
  uint8_t* volatile out = nullptr;
  if (setjmp(jerr.jb)) {
    jpeg_destroy_decompress(&cinfo);
    free(out);
    set_error("jpeg decode failed");
    return nullptr;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, const_cast<uint8_t*>(buf), len);
  jpeg_read_header(&cinfo, TRUE);
  cinfo.out_color_space = want_color ? JCS_RGB : JCS_GRAYSCALE;
  jpeg_start_decompress(&cinfo);
  *w = cinfo.output_width;
  *h = cinfo.output_height;
  *c = cinfo.output_components;
  int stride = (*w) * (*c);
  out = (uint8_t*)malloc((size_t)(*h) * stride);
  while (cinfo.output_scanline < cinfo.output_height) {
    uint8_t* row = out + (size_t)cinfo.output_scanline * stride;
    jpeg_read_scanlines(&cinfo, &row, 1);
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  return out;
}

// ------------------------------------------------------------------ //
// Prefetcher: worker threads read (+ optionally decode) records ahead
// into a bounded queue — the role of ImageRecordIOParser2's OMP pool +
// PrefetcherIter's background thread.
// ------------------------------------------------------------------ //
struct Item {
  int64_t index = -1;
  uint8_t* data = nullptr;  // record bytes or decoded pixels
  int64_t len = 0;
  int w = 0, h = 0, c = 0;  // set when decoded
  bool ok = false;
};

struct Prefetcher {
  Reader* reader = nullptr;
  std::vector<int64_t> order;
  std::atomic<size_t> next_fetch{0};
  size_t next_emit = 0;  // order position to hand out next (in-order)
  int decode = 0;        // 0: raw bytes; 1: jpeg->RGB
  int skip_header = 0;   // bytes to skip before jpeg payload (IRHeader+label)
  size_t capacity = 16;

  std::mutex mu;
  std::condition_variable cv_ready, cv_space;
  std::deque<Item> ready;  // completed items, arbitrary order
  std::vector<Item> stash;  // out-of-order completions
  std::vector<std::thread> workers;
  std::atomic<bool> stop_flag{false};

  ~Prefetcher() { shutdown(); }

  void shutdown() {
    stop_flag = true;
    cv_space.notify_all();
    cv_ready.notify_all();
    for (auto& t : workers)
      if (t.joinable()) t.join();
    workers.clear();
    for (auto& it : ready) free(it.data);
    for (auto& it : stash) free(it.data);
    ready.clear();
    stash.clear();
  }

  void start(int num_threads) {
    for (int t = 0; t < num_threads; ++t)
      workers.emplace_back([this] { work(); });
  }

  void work() {
    while (!stop_flag) {
      size_t pos = next_fetch.fetch_add(1);
      if (pos >= order.size()) return;
      Item it;
      it.index = pos;
      int64_t len = 0;
      uint8_t* rec = reader->read(order[pos], &len);
      if (rec && decode) {
        int64_t off = skip_header;
        // variable-length label vector: IRHeader.flag floats after header
        if (off >= 4 && len >= 4) {
          uint32_t flag;
          memcpy(&flag, rec, 4);
          off = skip_header + 4 * (int64_t)flag;
        }
        if (off < len) {
          it.data = decode_jpeg(rec + off, len - off, 1, &it.w, &it.h, &it.c);
          it.len = it.data ? (int64_t)it.w * it.h * it.c : 0;
          it.ok = it.data != nullptr;
        }
        free(rec);
      } else {
        it.data = rec;
        it.len = len;
        it.ok = rec != nullptr;
      }
      std::unique_lock<std::mutex> lk(mu);
      // always admit the item the consumer is waiting for (index ==
      // next_emit), even at capacity — otherwise a slow record 0 plus a
      // full queue of later indices deadlocks the pipeline
      cv_space.wait(lk, [this, &it] {
        return stop_flag || ready.size() + stash.size() < capacity ||
               (size_t)it.index == next_emit;
      });
      if (stop_flag) {
        free(it.data);
        return;
      }
      ready.push_back(it);
      cv_ready.notify_all();
    }
  }

  // next item in submission order; blocks. returns false at end.
  bool next(Item* out) {
    std::unique_lock<std::mutex> lk(mu);
    while (true) {
      for (size_t i = 0; i < stash.size(); ++i) {
        if ((size_t)stash[i].index == next_emit) {
          *out = stash[i];
          stash.erase(stash.begin() + i);
          ++next_emit;
          cv_space.notify_all();
          return true;
        }
      }
      for (size_t i = 0; i < ready.size(); ++i) {
        if ((size_t)ready[i].index == next_emit) {
          *out = ready[i];
          ready.erase(ready.begin() + i);
          ++next_emit;
          cv_space.notify_all();
          return true;
        }
      }
      // move stragglers to stash
      while (!ready.empty()) {
        stash.push_back(ready.front());
        ready.pop_front();
      }
      if (next_emit >= order.size()) return false;
      cv_ready.wait(lk);
      if (stop_flag) return false;
    }
  }
};

}  // namespace

// ------------------------------------------------------------------ //
// C ABI
// ------------------------------------------------------------------ //
extern "C" {

const char* mxio_last_error() { return g_error.c_str(); }

void* mxio_reader_open(const char* path, const char* idx_path) {
  auto* r = new Reader();
  if (!r->open(path, idx_path)) {
    delete r;
    return nullptr;
  }
  return r;
}

int64_t mxio_reader_count(void* h) {
  return ((Reader*)h)->offsets.size();
}

uint8_t* mxio_reader_read(void* h, int64_t i, int64_t* len) {
  return ((Reader*)h)->read(i, len);
}

void mxio_reader_close(void* h) { delete (Reader*)h; }

void mxio_free(void* p) { free(p); }

void* mxio_writer_open(const char* path, const char* idx_path) {
  auto* w = new Writer();
  if (!w->open(path, idx_path)) {
    delete w;
    return nullptr;
  }
  return w;
}

int mxio_writer_write(void* h, const uint8_t* buf, int64_t len) {
  return ((Writer*)h)->write(buf, len) ? 0 : -1;
}

void mxio_writer_close(void* h) { delete (Writer*)h; }

uint8_t* mxio_decode_jpeg(const uint8_t* buf, int64_t len, int want_color,
                          int* w, int* h, int* c) {
  return decode_jpeg(buf, len, want_color, w, h, c);
}

// prefetcher over reader handle; indices = iteration order (epoch perm).
// decode: 0=raw records, 1=jpeg RGB with skip_header bytes of IRHeader.
void* mxio_prefetch_create(void* reader, const int64_t* indices, int64_t n,
                           int num_threads, int capacity, int decode,
                           int skip_header) {
  auto* p = new Prefetcher();
  p->reader = (Reader*)reader;
  p->order.assign(indices, indices + n);
  p->decode = decode;
  p->skip_header = skip_header;
  p->capacity = capacity > 0 ? capacity : 16;
  p->start(num_threads > 0 ? num_threads : 2);
  return p;
}

// returns: 1 item ok, 0 end of stream, -1 decode error (item skipped
// upstream decides). data must be freed with mxio_free.
int mxio_prefetch_next(void* h, uint8_t** data, int64_t* len, int* w,
                       int* hh, int* c) {
  Item it;
  if (!((Prefetcher*)h)->next(&it)) return 0;
  *data = it.data;
  *len = it.len;
  *w = it.w;
  *hh = it.h;
  *c = it.c;
  return it.ok ? 1 : -1;
}

void mxio_prefetch_close(void* h) { delete (Prefetcher*)h; }

}  // extern "C"
