#!/usr/bin/env python3
"""Autoregressive generation with the KV-cache decoder.

Loads (or randomly initializes) a GPT model and samples continuations:

    python example/gpt_generate.py --new 64 --temperature 0.8 --top-k 40
    python example/gpt_generate.py --params model.params  # trained weights

The decoder (``mxnet_tpu.models.kv_generate``) compiles prefill+sampling
into ONE program — compare ``--mode full`` (the reference-style
recompute-per-token loop) to see why the cache matters.
"""
from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as onp


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--new", type=int, default=32)
    p.add_argument("--batch", type=int, default=2)
    p.add_argument("--prompt-len", type=int, default=8)
    p.add_argument("--temperature", type=float, default=0.8)
    p.add_argument("--top-k", type=int, default=40)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--mode", choices=["kv", "full"], default="kv")
    p.add_argument("--params", default="",
                   help="optional .params file of a trained GPT")
    p.add_argument("--layers", type=int, default=2)
    p.add_argument("--units", type=int, default=128)
    p.add_argument("--vocab", type=int, default=1024)
    args = p.parse_args(argv)

    import mxnet_tpu as mx
    from mxnet_tpu.models import GPT, GPTConfig, kv_generate

    mx.random.seed(args.seed)
    cfg = GPTConfig(vocab_size=args.vocab, max_length=512,
                    num_layers=args.layers, units=args.units,
                    num_heads=max(1, args.units // 32),
                    hidden_size=4 * args.units)
    net = GPT(cfg)
    net.initialize(mx.init.Normal(0.02))
    if args.params:
        net.load_parameters(args.params)

    prompt = onp.random.RandomState(args.seed).randint(
        0, cfg.vocab_size, (args.batch, args.prompt_len))
    t0 = time.time()
    if args.mode == "kv":
        out = kv_generate(net, prompt, max_new_tokens=args.new,
                          temperature=args.temperature, top_k=args.top_k,
                          seed=args.seed)
    else:
        out = net.generate(prompt, max_new_tokens=args.new,
                           temperature=args.temperature,
                           top_k=args.top_k, seed=args.seed)
    dt = time.time() - t0
    for row in out:
        print(" ".join(str(t) for t in row))
    print(f"# {args.mode}: {args.batch * args.new} tokens in {dt:.2f}s "
          f"({args.batch * args.new / dt:.1f} tok/s, incl. compile)",
          file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
