#!/usr/bin/env python3
"""Long-context attention via sequence parallelism (ring attention).

The reference has NO long-context machinery (SURVEY.md §5.7) — this is new
TPU-native capability: the sequence axis is sharded over the mesh, K/V
blocks rotate around the ring with ``ppermute`` (ICI-neighbor traffic
only), and each device folds remote blocks into an online softmax.
Per-device memory is O(L/n · L/n) instead of O(L²).

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python example/long_context_ring_attention.py --seq-len 8192
"""
from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as onp


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--seq-len", type=int, default=8192)
    p.add_argument("--heads", type=int, default=8)
    p.add_argument("--head-dim", type=int, default=64)
    p.add_argument("--batch", type=int, default=1)
    p.add_argument("--check", action="store_true",
                   help="verify against full attention (small seq only)")
    args = p.parse_args(argv)

    import jax
    import mxnet_tpu as mx
    from mxnet_tpu import parallel

    ndev = len(jax.devices())
    if args.seq_len % ndev:
        raise SystemExit(f"--seq-len must divide the {ndev}-device ring")
    mesh = parallel.make_mesh({"sp": ndev})
    with parallel.use_mesh(mesh):
        rng = onp.random.RandomState(0)
        shape = (args.batch, args.heads, args.seq_len, args.head_dim)
        q = mx.nd.array(rng.randn(*shape).astype(onp.float32))
        k = mx.nd.array(rng.randn(*shape).astype(onp.float32))
        v = mx.nd.array(rng.randn(*shape).astype(onp.float32))

        t0 = time.time()
        out = mx.nd.ring_attention(q, k, v, causal=True, axis="sp",
                                   mesh=mesh)
        out.wait_to_read()
        print(f"ring attention over {ndev}-device ring: seq={args.seq_len} "
              f"-> {out.shape} in {time.time() - t0:.2f}s "
              f"(per-device seq shard {args.seq_len // ndev})")

        if args.check:
            ref = mx.nd.flash_attention(q, k, v, causal=True)
            err = float(onp.abs(out.asnumpy() - ref.asnumpy()).max())
            print(f"max |ring - full| = {err:.2e}")
            assert err < 5e-5
    return 0


if __name__ == "__main__":
    sys.exit(main())
