#!/usr/bin/env python3
"""Legacy Module-API MLP on MNIST (reference
``example/image-classification/train_mnist.py`` workflow): symbolic graph,
``mod.fit``, epoch checkpoints via ``mx.callback.do_checkpoint``.

    python example/module_mnist_mlp.py --epochs 3
"""
from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def build_sym():
    import mxnet_tpu as mx
    data = mx.sym.var("data")
    label = mx.sym.var("softmax_label")
    h = mx.sym.FullyConnected(data, mx.sym.var("fc1_weight"),
                              mx.sym.var("fc1_bias"), num_hidden=128,
                              name="fc1")
    h = mx.sym.Activation(h, act_type="relu")
    h = mx.sym.FullyConnected(h, mx.sym.var("fc2_weight"),
                              mx.sym.var("fc2_bias"), num_hidden=64,
                              name="fc2")
    h = mx.sym.Activation(h, act_type="relu")
    h = mx.sym.FullyConnected(h, mx.sym.var("fc3_weight"),
                              mx.sym.var("fc3_bias"), num_hidden=10,
                              name="fc3")
    return mx.sym.SoftmaxOutput(h, label, name="softmax")


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=3)
    p.add_argument("--batch-size", type=int, default=100)
    p.add_argument("--lr", type=float, default=0.1)
    p.add_argument("--synthetic", type=int, default=0)
    p.add_argument("--checkpoint-prefix", default=None)
    args = p.parse_args(argv)

    import mxnet_tpu as mx
    from mxnet_tpu.gluon.data.vision import MNIST
    from mxnet_tpu.module import Module

    try:
        ds = MNIST(train=True, synthetic=args.synthetic)
    except Exception:
        print("MNIST not found; using synthetic data")
        ds = MNIST(train=True, synthetic=args.synthetic or 2000)
    X = ds._data.reshape(len(ds), -1).astype("float32") / 255.0
    y = ds._label.astype("float32")
    it = mx.io.NDArrayIter(X, y, batch_size=args.batch_size, shuffle=True)

    ctx = mx.tpu() if mx.context.num_tpus() else mx.cpu()
    mod = Module(build_sym(), context=ctx)
    cbs = []
    if args.checkpoint_prefix:
        cbs.append(mx.callback.do_checkpoint(args.checkpoint_prefix))
    mod.fit(it, num_epoch=args.epochs, optimizer="sgd",
            optimizer_params=(("learning_rate", args.lr),
                              ("momentum", 0.9)),
            initializer=mx.init.Xavier(),
            epoch_end_callback=cbs or None,
            batch_end_callback=mx.callback.Speedometer(args.batch_size, 10))
    print("final:", mod.score(it, "acc"))
    return 0


if __name__ == "__main__":
    sys.exit(main())
