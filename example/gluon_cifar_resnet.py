#!/usr/bin/env python3
"""Train ResNet-18 (thumbnail) on CIFAR-10 with the Gluon API
(reference ``example/image-classification`` workflow).

Uses real CIFAR-10 from ``--data-dir`` when present, else deterministic
synthetic data (the reference's ``--benchmark 1`` dummy-data mode).

    python example/gluon_cifar_resnet.py --epochs 2 --batch-size 64
"""
from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as onp


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=2)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--lr", type=float, default=0.05)
    p.add_argument("--data-dir", default=os.path.join("~", ".mxnet",
                                                      "datasets", "cifar10"))
    p.add_argument("--synthetic", type=int, default=0,
                   help="use N synthetic samples instead of real CIFAR")
    p.add_argument("--hybridize", type=int, default=1)
    p.add_argument("--eval", type=int, default=1,
                   help="evaluate test-split accuracy each epoch")
    p.add_argument("--lr-decay-epochs", type=str, default="",
                   help="comma-separated epochs at which lr *= 0.1")
    args = p.parse_args(argv)

    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon
    from mxnet_tpu.gluon.data.vision import CIFAR10
    from mxnet_tpu.gluon.model_zoo.vision import get_resnet

    ctx = mx.tpu() if mx.context.num_tpus() else mx.cpu()
    # per-BATCH device-side normalization: per-sample nd transforms would
    # dispatch one device op per image (disastrous through a TPU tunnel;
    # the reference normalizes on the CPU side of the pipeline)
    mean = mx.nd.array(onp.array([0.4914, 0.4822, 0.4465],
                                 onp.float32).reshape(1, 3, 1, 1))
    std = mx.nd.array(onp.array([0.2470, 0.2435, 0.2616],
                                onp.float32).reshape(1, 3, 1, 1))

    mean = mean.as_in_context(ctx)
    std = std.as_in_context(ctx)

    def prep(x):
        # x: uint8 NHWC batch -> normalized float NCHW on device
        x = x.astype("float32").as_in_context(ctx)
        x = x.transpose((0, 3, 1, 2)) / 255.0
        return (x - mean) / std

    try:
        train = CIFAR10(root=args.data_dir, train=True,
                        synthetic=args.synthetic)
    except Exception:
        print("CIFAR-10 not found; falling back to synthetic data")
        train = CIFAR10(train=True, synthetic=args.synthetic or 512)
    test = None
    if args.eval:
        try:
            test = CIFAR10(root=args.data_dir, train=False,
                           synthetic=args.synthetic and
                           max(1000, args.synthetic // 5))
        except Exception:
            test = CIFAR10(train=False, synthetic=1000)

    # numpy-level batching: ONE host->device transfer per batch (a
    # per-sample DataLoader would pay one transfer per image — ruinous
    # over a remote TPU tunnel)
    def batches(ds, bs, shuffle, rng, drop_last=True):
        data, labels = ds._data, ds._label
        order = rng.permutation(len(labels)) if shuffle else \
            onp.arange(len(labels))
        stop = len(order) - bs + 1 if drop_last else len(order)
        for lo in range(0, max(stop, 0 if drop_last else 1), bs):
            idx = order[lo:lo + bs]
            if len(idx) == 0:
                return
            yield mx.nd.array(data[idx]), mx.nd.array(
                labels[idx].astype(onp.float32))

    net = get_resnet(1, 18, thumbnail=True, classes=10)
    net.initialize(mx.init.Xavier(), ctx=ctx)
    if args.hybridize:
        net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": args.lr, "momentum": 0.9,
                             "wd": 1e-4})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    metric = mx.metric.Accuracy()

    decay_epochs = {int(e) for e in args.lr_decay_epochs.split(",") if e}
    for epoch in range(args.epochs):
        if epoch in decay_epochs:
            trainer.set_learning_rate(trainer.learning_rate * 0.1)
        metric.reset()
        tic = time.time()
        n = 0
        rng = onp.random.RandomState(epoch)
        for x, y in batches(train, args.batch_size, True, rng):
            x = prep(x)
            y = y.as_in_context(ctx)
            with autograd.record():
                out = net(x)
                loss = loss_fn(out, y)
            loss.backward()
            trainer.step(x.shape[0])
            metric.update(y, out)
            n += x.shape[0]
        name, acc = metric.get()
        dt = time.time() - tic
        line = f"epoch {epoch}: {name}={acc:.4f} ({n / dt:.0f} samples/s)"
        if test is not None:
            vmetric = mx.metric.Accuracy()
            for x, y in batches(test, args.batch_size, False,
                                onp.random.RandomState(0),
                                drop_last=False):
                x = prep(x)
                y = y.as_in_context(ctx)
                vmetric.update(y, net(x))
            line += f" val-acc={vmetric.get()[1]:.4f}"
        print(line, flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
