#!/usr/bin/env python3
"""Train ResNet-18 (thumbnail) on CIFAR-10 with the Gluon API
(reference ``example/image-classification`` workflow).

Uses real CIFAR-10 from ``--data-dir`` when present, else deterministic
synthetic data (the reference's ``--benchmark 1`` dummy-data mode).

    python example/gluon_cifar_resnet.py --epochs 2 --batch-size 64
"""
from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as onp


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=2)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--lr", type=float, default=0.05)
    p.add_argument("--data-dir", default=os.path.join("~", ".mxnet",
                                                      "datasets", "cifar10"))
    p.add_argument("--synthetic", type=int, default=0,
                   help="use N synthetic samples instead of real CIFAR")
    p.add_argument("--hybridize", type=int, default=1)
    p.add_argument("--eval", type=int, default=1,
                   help="evaluate test-split accuracy each epoch")
    p.add_argument("--lr-decay-epochs", type=str, default="",
                   help="comma-separated epochs at which lr *= 0.1")
    args = p.parse_args(argv)

    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon
    from mxnet_tpu.gluon.data import DataLoader
    from mxnet_tpu.gluon.data.vision import CIFAR10, transforms as T
    from mxnet_tpu.gluon.model_zoo.vision import get_resnet

    ctx = mx.tpu() if mx.context.num_tpus() else mx.cpu()
    transform = T.Compose([T.ToTensor(),
                           T.Normalize([0.4914, 0.4822, 0.4465],
                                       [0.2470, 0.2435, 0.2616])])
    try:
        train = CIFAR10(root=args.data_dir, train=True,
                        synthetic=args.synthetic)
    except Exception:
        print("CIFAR-10 not found; falling back to synthetic data")
        train = CIFAR10(train=True, synthetic=args.synthetic or 512)
    loader = DataLoader(train.transform_first(transform),
                        batch_size=args.batch_size, shuffle=True,
                        num_workers=2, last_batch="discard")
    val_loader = None
    if args.eval:
        try:
            test = CIFAR10(root=args.data_dir, train=False,
                           synthetic=args.synthetic and
                           max(1000, args.synthetic // 5))
        except Exception:
            test = CIFAR10(train=False, synthetic=1000)
        val_loader = DataLoader(test.transform_first(transform),
                                batch_size=args.batch_size, shuffle=False,
                                num_workers=2)

    net = get_resnet(1, 18, thumbnail=True, classes=10)
    net.initialize(mx.init.Xavier(), ctx=ctx)
    if args.hybridize:
        net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": args.lr, "momentum": 0.9,
                             "wd": 1e-4})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    metric = mx.metric.Accuracy()

    decay_epochs = {int(e) for e in args.lr_decay_epochs.split(",") if e}
    for epoch in range(args.epochs):
        if epoch in decay_epochs:
            trainer.set_learning_rate(trainer.learning_rate * 0.1)
        metric.reset()
        tic = time.time()
        n = 0
        for x, y in loader:
            x = x.as_in_context(ctx)
            y = y.astype("float32").as_in_context(ctx)
            with autograd.record():
                out = net(x)
                loss = loss_fn(out, y)
            loss.backward()
            trainer.step(x.shape[0])
            metric.update(y, out)
            n += x.shape[0]
        name, acc = metric.get()
        dt = time.time() - tic
        line = f"epoch {epoch}: {name}={acc:.4f} ({n / dt:.0f} samples/s)"
        if val_loader is not None:
            vmetric = mx.metric.Accuracy()
            for x, y in val_loader:
                x = x.as_in_context(ctx)
                y = y.astype("float32").as_in_context(ctx)
                vmetric.update(y, net(x))
            line += f" val-acc={vmetric.get()[1]:.4f}"
        print(line, flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
