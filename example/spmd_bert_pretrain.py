#!/usr/bin/env python3
"""BERT-base MLM pretraining with the fused SPMD train step over a device
mesh (the TPU-native form of the reference's GluonNLP BERT recipe;
SURVEY.md §6 config 3).

On one chip the mesh is {dp:1}; on a pod slice set --dp/--tp to shard.
Synthetic token streams keep it hermetic (reference --benchmark mode).

    python example/spmd_bert_pretrain.py --steps 20 --batch-size 64
"""
from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as onp


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--seq-len", type=int, default=128)
    p.add_argument("--layers", type=int, default=12)
    p.add_argument("--dp", type=int, default=0, help="data-parallel size "
                   "(default: all devices)")
    p.add_argument("--tp", type=int, default=1, help="tensor-parallel size")
    p.add_argument("--lr", type=float, default=1e-4)
    args = p.parse_args(argv)

    import jax
    import mxnet_tpu as mx
    from mxnet_tpu import gluon, parallel
    from mxnet_tpu.models import BERTModel, BERTConfig

    mx.random.seed(0)
    ndev = len(jax.devices())
    dp = args.dp or max(1, ndev // args.tp)
    on_tpu = jax.devices()[0].platform == "tpu"

    cfg = BERTConfig(vocab_size=30528, max_length=args.seq_len,
                     num_layers=args.layers, units=768, num_heads=12,
                     hidden_size=3072,
                     dtype="bfloat16" if on_tpu else "float32")
    bert = BERTModel(cfg, use_pooler=False, use_mlm=True)

    class MLMHead(gluon.Block):
        def __init__(self):
            super().__init__()
            self.bert = bert

        def forward(self, tokens):
            return self.bert(tokens)[-1]

    net = MLMHead()
    net.initialize(mx.init.Normal(0.02))
    axes = {"dp": dp}
    if args.tp > 1:
        axes["tp"] = args.tp
    mesh = parallel.make_mesh(axes)
    trainer = parallel.SPMDTrainer(net,
                                   gluon.loss.SoftmaxCrossEntropyLoss(),
                                   "adamw", {"learning_rate": args.lr},
                                   mesh=mesh)

    rng = onp.random.RandomState(0)
    toks = mx.nd.array(rng.randint(0, cfg.vocab_size,
                                   (args.batch_size, args.seq_len)))
    labels = mx.nd.array(rng.randint(0, cfg.vocab_size,
                                     (args.batch_size, args.seq_len)))
    # warmup/compile
    float(onp.asarray(trainer.step(toks, labels).asnumpy()).reshape(()))
    t0 = time.perf_counter()
    loss = None
    for i in range(args.steps):
        loss = trainer.step(toks, labels)
    final = float(onp.asarray(loss.asnumpy()).reshape(()))
    dt = time.perf_counter() - t0
    toks_per_s = args.batch_size * args.seq_len * args.steps / dt
    print(f"mesh={dict(zip(mesh.axis_names, mesh.devices.shape))} "
          f"loss={final:.4f} {toks_per_s:.0f} tokens/s "
          f"({toks_per_s / max(ndev,1):.0f}/device)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
